"""Query admission control & QoS scheduling (pilosa_tpu/sched/).

Unit tests drive the AdmissionController on an injectable clock (no real
sleeps for deadline logic); the saturation tests boot a real node and
assert the acceptance contract: in-flight executions never exceed
max-concurrent-queries, excess queries get 429 + Retry-After instead of
unbounded queueing, interactive dequeues ahead of batch, shed queries
leave no queue residue (the conftest leak guard re-checks), and the
scheduler's load feed pushes CountBatcher rounds to >= 4 calls."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.exec import batcher as batchmod
from pilosa_tpu.exec.batcher import CountBatcher
from pilosa_tpu.pql import parse
from pilosa_tpu.sched.admission import AdmissionController, ShedError
from pilosa_tpu.sched.cost import QueryCost, estimate
from pilosa_tpu.testing import ClusterHarness
from pilosa_tpu.utils.stats import StatsClient


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# cost estimation
# ---------------------------------------------------------------------------


class TestCost:
    def test_bsi_heavier_than_plain_row(self):
        plain = estimate(None, parse("Count(Row(f=1))"), shards=[0])
        bsi = estimate(None, parse("Count(Row(v > 7))"), shards=[0])
        assert plain.device_bytes > 0
        assert bsi.device_bytes > plain.device_bytes

    def test_writes_carry_no_device_weight(self):
        w = estimate(None, parse("Set(1, f=1)"), shards=[0])
        assert w.write
        assert w.device_bytes == 0

    def test_more_shards_cost_more(self):
        one = estimate(None, parse("Count(Row(f=1))"), shards=[0])
        four = estimate(None, parse("Count(Row(f=1))"), shards=[0, 1, 2, 3])
        assert four.device_bytes == 4 * one.device_bytes

    def test_raw_text_and_garbage_never_raise(self):
        assert estimate(None, "Count(Row(f=1))").sweeps >= 1
        assert estimate(None, "This(Is(Not PQL").device_bytes == 0


# ---------------------------------------------------------------------------
# AdmissionController units (injectable clock, no server)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_immediate_admit_and_release(self):
        ctl = AdmissionController(max_concurrent=2, clock=FakeClock())
        t1 = ctl.admit()
        t2 = ctl.admit(cls="batch")
        assert ctl.pending() == (0, 2)
        t1.release()
        t2.release()
        t2.release()  # idempotent
        assert ctl.pending() == (0, 0)

    def test_unknown_class_falls_back_to_default(self):
        ctl = AdmissionController(default_class="batch")
        t = ctl.admit(cls="platinum")
        assert t.cls == "batch"
        t.release()

    def test_queued_grant_on_release(self):
        ctl = AdmissionController(max_concurrent=1)
        t1 = ctl.admit()
        got = []
        th = threading.Thread(
            target=lambda: got.append(ctl.admit()), daemon=True
        )
        th.start()
        _wait_until(lambda: ctl.queue_depth() == 1, what="waiter queued")
        assert ctl.pending() == (1, 1)
        t1.release()
        th.join(5)
        assert got and got[0].waited >= 0.0
        got[0].release()
        assert ctl.pending() == (0, 0)

    def test_shed_when_queue_full_carries_retry_after(self):
        ctl = AdmissionController(
            max_concurrent=1, queue_depth=0, retry_after=3.5
        )
        t1 = ctl.admit()
        with pytest.raises(ShedError) as ei:
            ctl.admit()
        assert ei.value.retry_after == 3.5
        assert ei.value.status == 429
        t1.release()
        assert ctl.pending() == (0, 0)

    def test_deadline_exhausted_on_arrival_sheds(self):
        ctl = AdmissionController(clock=FakeClock())
        with pytest.raises(ShedError):
            ctl.admit(deadline=0.0)
        assert ctl.pending() == (0, 0)

    def test_deadline_expiring_in_queue_sheds_without_residue(self):
        clock = FakeClock()
        ctl = AdmissionController(max_concurrent=1, clock=clock)
        t1 = ctl.admit()
        sheds = []
        def waiter():
            try:
                ctl.admit(deadline=1.0)
            except ShedError as e:
                sheds.append(e)
        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        _wait_until(lambda: ctl.queue_depth() == 1, what="waiter queued")
        clock.advance(2.0)  # its deadline is now in the past
        t1.release()  # pump purges the expired head and wakes it
        th.join(5)
        assert sheds, "expired waiter must shed, not run"
        assert ctl.pending() == (0, 0)

    def test_weighted_fair_interactive_dequeues_ahead_of_batch(self):
        ctl = AdmissionController(max_concurrent=1)
        filler = ctl.admit(cls="batch")
        order = []
        olock = threading.Lock()

        def worker(cls):
            t = ctl.admit(cls=cls)
            with olock:
                order.append(cls)
            t.release()

        threads = []
        # enqueue batch FIRST: arrival order must not beat class weight
        for i, cls in enumerate(
            ["batch", "batch", "batch", "interactive", "interactive",
             "interactive"]
        ):
            th = threading.Thread(target=worker, args=(cls,), daemon=True)
            th.start()
            threads.append(th)
            _wait_until(
                lambda n=i: ctl.queue_depth() == n + 1, what="enqueue"
            )
        filler.release()
        for th in threads:
            th.join(5)
        assert order == ["interactive"] * 3 + ["batch"] * 3
        assert ctl.pending() == (0, 0)

    def test_byte_budget_gates_inflight(self):
        ctl = AdmissionController(max_concurrent=8, byte_budget=100)
        t1 = ctl.admit(cost=QueryCost(device_bytes=60))
        granted = []
        th = threading.Thread(
            target=lambda: granted.append(
                ctl.admit(cost=QueryCost(device_bytes=60))
            ),
            daemon=True,
        )
        th.start()
        _wait_until(lambda: ctl.queue_depth() == 1, what="byte-gated waiter")
        assert not granted  # 60 + 60 > 100: must wait despite free slots
        t1.release()
        th.join(5)
        assert granted
        granted[0].release()
        assert ctl.pending() == (0, 0)

    def test_oversized_query_still_runs_alone(self):
        ctl = AdmissionController(max_concurrent=8, byte_budget=100)
        t = ctl.admit(cost=QueryCost(device_bytes=10_000))
        assert ctl.pending() == (0, 1)
        t.release()

    def test_stats_emitted(self):
        st = StatsClient()
        ctl = AdmissionController(
            max_concurrent=1, queue_depth=0, stats=st
        )
        t = ctl.admit()
        with pytest.raises(ShedError):
            ctl.admit(cls="batch")
        t.release()
        snap = st.registry.snapshot()
        # admit/shed carry class AND index labels ("-" = no index bound);
        # shed additionally carries the reason taxonomy tag
        assert snap.get("sched.admit;class:interactive,index:-") == 1
        assert snap.get("sched.shed;class:batch,index:-,reason:queue") == 1
        assert "sched.queue_depth" in snap
        assert "sched.inflight" in snap


# ---------------------------------------------------------------------------
# adaptive batching: scheduler load feeds CountBatcher
# ---------------------------------------------------------------------------


def test_adaptive_batching_reaches_queue_depth(monkeypatch):
    """With the scheduler reporting load >= 4, a CountBatcher leader
    holds until 4 calls line up and runs them as ONE merged round —
    observable via the batcher.batch_size stat (acceptance criterion)."""
    for k in batchmod.STATS:
        batchmod.STATS[k] = 0
    ctl = AdmissionController(max_concurrent=8)
    st = StatsClient()
    b = CountBatcher()
    b.stats = st
    b.load_hint = ctl.load  # the NodeServer wiring, minus the server
    b.hold_timeout = 2.0  # generous: determinism over latency in tests
    # 4 batchable (pure-Count) queries in flight on index "i"
    tickets = [ctl.admit(batchable=True, index="i") for _ in range(4)]
    results = {}

    def client(i):
        results[i] = b.run(
            "i",
            parse("Count(Row(f=1))"),
            lambda q: list(range(len(q.calls))),
        )

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(4)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(10)
    for t in tickets:
        t.release()
    assert all(len(r) == 1 for r in results.values())
    assert batchmod.STATS["merged_execs"] == 1  # ONE merged dispatch
    hist = st.registry.snapshot().get("batcher.batch_size")
    assert hist is not None and hist["max"] >= 4


# ---------------------------------------------------------------------------
# saturation over a real node (HTTP)
# ---------------------------------------------------------------------------


def _post_query(uri, index, pql, headers=None):
    req = urllib.request.Request(
        f"{uri}/index/{index}/query",
        data=json.dumps({"query": pql}).encode(),
        method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        return resp.status, json.loads(resp.read())


def _gated_executor(srv):
    """Wrap the node's executor so executions block on a gate while the
    test builds up saturation; records peak concurrency + order."""
    orig = srv.executor.execute_response
    state = {"cur": 0, "max": 0, "order": []}
    lock = threading.Lock()
    gate = threading.Event()

    def gated(index, query, shards=None, opt=None, **kw):
        with lock:
            state["cur"] += 1
            state["max"] = max(state["max"], state["cur"])
            state["order"].append(str(query))
        try:
            gate.wait(15)
            return orig(index, query, shards=shards, opt=opt, **kw)
        finally:
            with lock:
                state["cur"] -= 1

    srv.executor.execute_response = gated
    return gate, state


def test_saturation_sheds_429_and_bounds_inflight():
    with ClusterHarness(
        1,
        in_memory=True,
        max_concurrent_queries=2,
        admission_queue_depth=2,
        shed_retry_after=7.5,
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("sat")
        srv.api.create_field("sat", "f", {"type": "set"})
        srv.api.query("sat", "Set(1, f=1)")
        gate, state = _gated_executor(srv)
        outcomes = []
        olock = threading.Lock()

        def client():
            try:
                status, _ = _post_query(uri, "sat", "Row(f=1)")
                with olock:
                    outcomes.append((status, None))
            except urllib.error.HTTPError as e:
                with olock:
                    outcomes.append(
                        (
                            e.code,
                            (
                                e.headers.get("Retry-After"),
                                e.headers.get("X-Pilosa-Retry-After"),
                            ),
                        )
                    )
                e.close()

        threads = [
            threading.Thread(target=client, daemon=True) for _ in range(8)
        ]
        for th in threads:
            th.start()
        # 2 executing + 2 queued + 4 shed, all before the gate opens
        _wait_until(
            lambda: len(outcomes) == 4
            and state["cur"] == 2
            and srv.scheduler.queue_depth() == 2,
            what="saturation to settle (4 sheds, 2 executing, 2 queued)",
        )
        # shed queries carry 429 + the configured Retry-After: RFC
        # delta-seconds (integer) on the standard header, the precise
        # value on the vendor header
        assert all(code == 429 for code, _ in outcomes)
        assert all(ra == ("8", "7.5") for _, ra in outcomes)
        gate.set()
        for th in threads:
            th.join(15)
        assert len(outcomes) == 8
        assert sorted(code for code, _ in outcomes) == [200] * 4 + [429] * 4
        # admitted in-flight executions never exceeded the cap
        assert state["max"] <= 2
        # no shed query left queue residue
        assert srv.scheduler.pending() == (0, 0)
        # acceptance: sched stats visible on /metrics
        with urllib.request.urlopen(f"{uri}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "pilosa_tpu_sched_queue_depth" in text
        assert "pilosa_tpu_sched_shed" in text
        assert "pilosa_tpu_sched_wait_ms_count" in text
        assert "pilosa_tpu_sched_admit" in text


def test_priority_header_orders_dequeue_over_http():
    with ClusterHarness(
        1,
        in_memory=True,
        max_concurrent_queries=1,
        admission_queue_depth=8,
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("pri")
        srv.api.create_field("pri", "f", {"type": "set"})
        srv.api.query("pri", "Set(1, f=1) Set(1, f=2) Set(1, f=3)")
        gate, state = _gated_executor(srv)
        threads = []

        def client(pql, cls):
            def run():
                try:
                    _post_query(
                        uri, "pri", pql, headers={"X-Pilosa-Priority": cls}
                    )
                except urllib.error.HTTPError as e:
                    e.close()

            th = threading.Thread(target=run, daemon=True)
            th.start()
            threads.append(th)

        client("Row(f=1)", "batch")  # filler: occupies the single slot
        _wait_until(lambda: state["cur"] == 1, what="filler executing")
        # batch legs enqueue FIRST; interactive must still dequeue ahead
        for pql, cls in [
            ("Row(f=11)", "batch"),
            ("Row(f=12)", "batch"),
            ("Row(f=21)", "interactive"),
            ("Row(f=22)", "interactive"),
        ]:
            n_before = srv.scheduler.queue_depth()
            client(pql, cls)
            _wait_until(
                lambda n=n_before: srv.scheduler.queue_depth() == n + 1,
                what="leg queued",
            )
        gate.set()
        for th in threads:
            th.join(15)
        order = [q for q in state["order"] if "f=1)" not in q]
        interactive_pos = [
            i for i, q in enumerate(order) if "f=2" in q
        ]
        batch_pos = [i for i, q in enumerate(order) if "f=1" in q]
        assert max(interactive_pos) < min(batch_pos), order
        assert srv.scheduler.pending() == (0, 0)


def test_exhausted_internode_deadline_sheds_early():
    """A leg arriving with an already-spent X-Pilosa-Deadline budget is
    shed immediately (429, retryable) instead of timing out late."""
    with ClusterHarness(1, in_memory=True) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("dl")
        srv.api.create_field("dl", "f", {"type": "set"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_query(
                uri, "dl", "Row(f=1)", headers={"X-Pilosa-Deadline": "0"}
            )
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") is not None
        ei.value.close()
        assert srv.scheduler.pending() == (0, 0)


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------


def test_uncontended_grants_bank_no_wfq_credit():
    """Fast-path (uncontended) grants must not advance WFQ virtual time:
    a long interactive-only warmup would otherwise bank enough lag that
    batch dequeues FIRST when contention starts — priority inversion."""
    ctl = AdmissionController(max_concurrent=1)
    for _ in range(50):
        ctl.admit().release()  # interactive warmup, all uncontended
    filler = ctl.admit()
    order = []
    olock = threading.Lock()

    def worker(cls):
        t = ctl.admit(cls=cls)
        with olock:
            order.append(cls)
        t.release()

    threads = []
    for i, cls in enumerate(["batch", "interactive"]):
        th = threading.Thread(target=worker, args=(cls,), daemon=True)
        th.start()
        threads.append(th)
        _wait_until(lambda n=i: ctl.queue_depth() == n + 1, what="enqueue")
    filler.release()
    for th in threads:
        th.join(5)
    assert order == ["interactive", "batch"]
    assert ctl.pending() == (0, 0)


def test_expired_head_unblocks_queue_without_a_release():
    """A byte-gated head expiring in the queue must pump: entries behind
    it that now fit run immediately, not at the next ticket release."""
    clock = FakeClock()
    ctl = AdmissionController(max_concurrent=4, byte_budget=100, clock=clock)
    t1 = ctl.admit(cost=QueryCost(device_bytes=60))
    sheds, grants = [], []

    def fat():
        try:
            ctl.admit(cost=QueryCost(device_bytes=60), deadline=1.0)
        except ShedError as e:
            sheds.append(e)

    def cheap():
        grants.append(ctl.admit(cost=QueryCost(device_bytes=10)))

    tf = threading.Thread(target=fat, daemon=True)
    tf.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="fat queued")
    tc = threading.Thread(target=cheap, daemon=True)
    tc.start()
    _wait_until(lambda: ctl.queue_depth() == 2, what="cheap queued")
    clock.advance(2.0)  # fat's deadline passes; nothing releases
    tf.join(10)
    tc.join(10)
    assert sheds, "fat head must shed on its deadline"
    assert grants, "cheap entry must be granted by the shed's pump alone"
    grants[0].release()
    t1.release()
    assert ctl.pending() == (0, 0)


def test_load_hint_capped_at_concurrency_limit():
    """load() feeds the batcher's hold target; queued queries hold no
    ticket, so the hint must never exceed what can actually line up."""
    ctl = AdmissionController(max_concurrent=2, queue_depth=8)
    t1, t2 = ctl.admit(batchable=True), ctl.admit(batchable=True)
    threads = []
    for i in range(3):
        th = threading.Thread(
            target=lambda: ctl.admit(batchable=True).release(), daemon=True
        )
        th.start()
        threads.append(th)
        _wait_until(lambda n=i: ctl.queue_depth() == n + 1, what="queued")
    assert ctl.load() == 2  # min(2 inflight + 3 queued, cap 2)
    t1.release()
    t2.release()
    for th in threads:
        th.join(5)
    assert ctl.pending() == (0, 0)


def test_internode_429_is_breaker_neutral_and_honors_retry_after():
    """A loaded peer is not a dead peer: a 429 shed must not open the
    sender's circuit breaker, and the retry loop must honor the peer's
    Retry-After instead of the policy's (smaller) base backoff."""
    from pilosa_tpu.server import faults as fmod
    from pilosa_tpu.server.client import ClientError, InternalClient

    with ClusterHarness(
        1,
        in_memory=True,
        max_concurrent_queries=1,
        admission_queue_depth=0,
        shed_retry_after=0.01,
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("br")
        srv.api.create_field("br", "f", {"type": "set"})
        gate, state = _gated_executor(srv)
        th = threading.Thread(
            target=lambda: _post_query(uri, "br", "Row(f=1)"), daemon=True
        )
        th.start()
        _wait_until(lambda: state["cur"] == 1, what="slot occupied")
        sleeps = []
        reg = fmod.BreakerRegistry(threshold=1)
        policy = fmod.RetryPolicy(
            max_attempts=2, base_backoff=0.0001, sleep=sleeps.append
        )
        client = InternalClient(breakers=reg, retry_policy=policy)
        with pytest.raises(ClientError) as ei:
            client.query_node(uri, "br", "Count(Row(f=1))")
        assert ei.value.status == 429
        assert ei.value.retryable  # fan-out can fail over to a replica
        assert ei.value.retry_after == 0.01
        # both attempts shed, yet the breaker must stay closed
        assert reg.state(uri) == fmod.CLOSED
        assert sleeps and sleeps[-1] >= 0.01  # honored Retry-After
        gate.set()
        th.join(10)
        assert srv.scheduler.pending() == (0, 0)


def test_only_same_index_batchable_load_feeds_the_batcher_hint():
    """Row/TopN/remote traffic — and other indexes' Counts — can never
    join this index's count batch: a solo Count under mixed load must
    see load(index) <= 1 and pay no adaptive-hold window."""
    ctl = AdmissionController(max_concurrent=8)
    rows = [ctl.admit() for _ in range(3)]  # non-batchable in flight
    other = ctl.admit(batchable=True, index="other")  # different index
    assert ctl.load("i") == 0
    count = ctl.admit(batchable=True, index="i")
    assert ctl.load("i") == 1
    assert ctl.load("other") == 1
    count.release()
    other.release()
    for t in rows:
        t.release()
    assert ctl.pending() == (0, 0)


def test_class_debt_bounded_after_solo_saturation_epoch():
    """WFQ debt banked by a class that saturated alone must not starve
    it when mixed contention resumes later: re-activating classes are
    lifted to the global virtual clock, bounding the residual handicap
    to ~one service quantum (weight x a handful of grants, not the whole
    epoch)."""
    ctl = AdmissionController(max_concurrent=1, queue_depth=64)
    # batch-only saturated epoch: 3 CONTENDED batch grants bank debt
    filler = ctl.admit(cls="batch")
    for _ in range(3):
        nxt = []
        th = threading.Thread(
            target=lambda: nxt.append(ctl.admit(cls="batch")), daemon=True
        )
        th.start()
        _wait_until(lambda: ctl.queue_depth() == 1, what="epoch waiter")
        filler.release()
        th.join(5)
        filler = nxt[0]
    filler.release()  # idle: queues drained, nothing in flight
    assert ctl.pending() == (0, 0)
    # mixed contention resumes, interactive enqueued FIRST
    filler = ctl.admit()
    order = []
    olock = threading.Lock()

    def worker(cls):
        t = ctl.admit(cls=cls)
        with olock:
            order.append(cls)
        t.release()

    legs = ["interactive"] * 20 + ["batch"]
    threads = []
    for i, cls in enumerate(legs):
        th = threading.Thread(target=worker, args=(cls,), daemon=True)
        th.start()
        threads.append(th)
        _wait_until(lambda n=i: ctl.queue_depth() == n + 1, what="enqueue")
    filler.release()
    for th in threads:
        th.join(5)
    # batch re-enters with ~1 quantum of residual debt -> granted after
    # at most ~2 quanta of interactive (weight 8 each), NOT dead last
    assert "batch" in order
    assert order.index("batch") <= 17, order
    assert ctl.pending() == (0, 0)


def test_byte_gated_head_reserves_bytes_but_not_slots():
    """A byte-gated head blocks only its own class's FIFO and EARMARKS
    its bytes: zero-byte work (writes) from other classes still flows
    (work-conserving), but byte-weighted entries must not eat the
    earmark — a steady cheap stream could otherwise refill the budget
    forever and starve the gated head."""
    ctl = AdmissionController(max_concurrent=8, byte_budget=100)
    t1 = ctl.admit(cost=QueryCost(device_bytes=60))
    t2 = ctl.admit(cost=QueryCost(device_bytes=30))
    fat_grants, write_grants, cheap_grants = [], [], []
    tf = threading.Thread(
        target=lambda: fat_grants.append(
            ctl.admit(cost=QueryCost(device_bytes=60))
        ),
        daemon=True,
    )
    tf.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="fat queued")
    # zero-byte write in another class: granted around the gate
    tw = threading.Thread(
        target=lambda: write_grants.append(
            ctl.admit(cls="batch", cost=QueryCost(device_bytes=0))
        ),
        daemon=True,
    )
    tw.start()
    tw.join(5)
    assert write_grants, "zero-byte work must flow around a byte gate"
    # byte-weighted entry in another class: must NOT eat the earmark
    tc = threading.Thread(
        target=lambda: cheap_grants.append(
            ctl.admit(cls="internal", cost=QueryCost(device_bytes=20))
        ),
        daemon=True,
    )
    tc.start()
    _wait_until(lambda: ctl.queue_depth() == 2, what="cheap queued")
    t2.release()  # 60 in flight: fat still gated; cheap must stay queued
    time.sleep(0.05)
    assert not fat_grants and not cheap_grants
    assert ctl.queue_depth() == 2
    t1.release()  # earmark satisfied: fat runs first, then cheap fits
    tf.join(5)
    tc.join(5)
    assert fat_grants and cheap_grants
    write_grants[0].release()
    fat_grants[0].release()
    cheap_grants[0].release()
    assert ctl.pending() == (0, 0)


def test_ticket_released_even_when_span_construction_fails():
    """A failure anywhere after admission — even building the tracing
    span — must release the slot, or the node bleeds capacity into
    permanent 429s."""
    with ClusterHarness(1, in_memory=True, max_concurrent_queries=1) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("tl")
        srv.api.create_field("tl", "f", {"type": "set"})
        srv.api.query("tl", "Set(1, f=1)")

        class BoomTracer:
            def start_span(self, *a, **k):
                raise RuntimeError("boom")

            def start_span_from_headers(self, *a, **k):
                raise RuntimeError("boom")

        orig = srv.tracer
        srv.tracer = BoomTracer()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post_query(uri, "tl", "Row(f=1)")
            assert ei.value.code == 500
            ei.value.close()
        finally:
            srv.tracer = orig
        assert srv.scheduler.pending() == (0, 0)
        # the single slot was NOT leaked: the next query runs
        status, body = _post_query(uri, "tl", "Row(f=1)")
        assert status == 200 and body["results"][0]["columns"] == [1]


def test_learned_service_time_sheds_unmeetable_deadline_early():
    """Early shedding: once the controller has learned the service rate,
    a deadline that cannot be met from the back of the queue is rejected
    IMMEDIATELY (sender still has budget to re-map), not when it
    expires. Deadlines that fit still queue."""
    clock = FakeClock()
    ctl = AdmissionController(max_concurrent=1, clock=clock)
    t = ctl.admit()
    clock.advance(1.0)
    t.release()  # learned service time: ~1.0s per query
    filler = ctl.admit()
    with pytest.raises(ShedError) as ei:
        ctl.admit(deadline=0.5)  # est. wait ~1.0s > 0.5s budget
    assert "back of the queue" in str(ei.value)
    ok = []
    th = threading.Thread(
        target=lambda: ok.append(ctl.admit(deadline=10.0)), daemon=True
    )
    th.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="feasible leg queued")
    filler.release()
    th.join(5)
    assert ok, "a meetable deadline must queue, not shed"
    ok[0].release()
    assert ctl.pending() == (0, 0)


def test_attr_variant_counts_do_not_feed_batchable_hint():
    """Counts carrying columnAttrs/exclude* opts bypass the batcher, so
    they must not inflate the adaptive-batching load hint either."""
    from pilosa_tpu.exec.executor import ExecOptions

    with ClusterHarness(1, in_memory=True) as c:
        srv = c[0]
        srv.api.create_index("ba")
        srv.api.create_field("ba", "f", {"type": "set"})
        q = parse("Count(Row(f=1))")
        t = srv.api._admit(
            "ba", q, None, False, None, ExecOptions(column_attrs=True)
        )
        assert t is not None and not t.batchable
        assert srv.scheduler.load("ba") == 0
        t.release()
        t2 = srv.api._admit("ba", q, None, False, None, ExecOptions())
        assert t2.batchable and t2.index == "ba"
        assert srv.scheduler.load("ba") == 1
        t2.release()
        assert srv.scheduler.pending() == (0, 0)


def test_malformed_pql_still_counts_in_query_metrics():
    """Parsing moved ahead of the span/stat machinery (admission needs
    the call tree); a malformed-PQL flood must still register on query
    dashboards instead of looking like an idle node."""
    with ClusterHarness(1, in_memory=True) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("mm")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_query(uri, "mm", "Nope(((")
        assert ei.value.code == 400
        ei.value.close()
        snap = srv.stats.registry.snapshot()
        assert snap.get("query_n;index:mm") == 1


def test_internal_legs_ride_a_separate_lane():
    """Fan-out legs must not compete for coordinator slots — sharing
    them allows a distributed hold-and-wait (each node's coordinator
    holds its slot while its leg queues behind the peer's coordinator)."""
    ctl = AdmissionController(max_concurrent=1)
    coordinator = ctl.admit()  # the node's only coordinator slot
    leg = ctl.admit(cls="internal", leg=True)  # must NOT block
    assert leg.leg
    assert ctl.pending() == (0, 2)
    leg.release()
    coordinator.release()
    assert ctl.pending() == (0, 0)


def test_leg_lane_is_bounded_and_deadline_aware():
    ctl = AdmissionController(max_concurrent=1, queue_depth=0)
    l1 = ctl.admit(leg=True)
    with pytest.raises(ShedError):  # lane full, waiting bound 0
        ctl.admit(leg=True)
    with pytest.raises(ShedError):  # exhausted deadline sheds on arrival
        ctl.admit(leg=True, deadline=0.0)
    l1.release()
    l2 = ctl.admit(leg=True)  # released slot is reusable
    l2.release()
    assert ctl.pending() == (0, 0)


def test_concurrent_distributed_queries_with_single_slot_nodes():
    """Acceptance for the hold-and-wait fix: two nodes each coordinate a
    distributed query at the same time with max-concurrent-queries=1;
    both must complete well inside the deadline instead of deadlocking
    until it expires."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(
        2,
        in_memory=True,
        max_concurrent_queries=1,
        query_deadline=20.0,
    ) as c:
        c[0].api.create_index("dd")
        c[0].api.create_field("dd", "f", {"type": "set"})
        # bits on several shards so both nodes own some of the fan-out
        cols = [s * SHARD_WIDTH + 1 for s in range(8)]
        c[0].api.import_bits("dd", "f", [0] * len(cols), cols)
        results = {}
        errors = []

        def coordinate(i):
            try:
                results[i] = c[i].api.query("dd", "Count(Row(f=0))")[0]
            except Exception as e:  # noqa: BLE001 - surfaced in assert
                errors.append(e)

        threads = [
            threading.Thread(target=coordinate, args=(i,), daemon=True)
            for i in (0, 1)
        ]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join(15)
        elapsed = time.monotonic() - t0
        assert not errors, errors
        assert results == {0: 8, 1: 8}
        assert elapsed < 10, f"queries took {elapsed:.1f}s — hold-and-wait?"
        for srv in c.nodes:
            assert srv.scheduler.pending() == (0, 0)


def test_arrival_pump_grants_around_byte_gated_head():
    """Work-conserving on arrival: zero-byte work arriving behind a
    byte-gated fat head (slots free) must be granted immediately by the
    enqueue-time pump, not wait for the next release."""
    ctl = AdmissionController(max_concurrent=4, byte_budget=100)
    t1 = ctl.admit(cost=QueryCost(device_bytes=60))
    fat_grants = []
    tf = threading.Thread(
        target=lambda: fat_grants.append(
            ctl.admit(cost=QueryCost(device_bytes=60))
        ),
        daemon=True,
    )
    tf.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="fat queued")
    writes = []
    tc = threading.Thread(
        target=lambda: writes.append(
            ctl.admit(cls="batch", cost=QueryCost(device_bytes=0))
        ),
        daemon=True,
    )
    tc.start()
    tc.join(5)  # NO release happened: the arrival pump must grant it
    assert writes, "zero-byte arrival must be granted with slots free"
    writes[0].release()
    t1.release()
    tf.join(5)
    assert fat_grants
    fat_grants[0].release()
    assert ctl.pending() == (0, 0)


def test_done_batching_drops_hint_before_release():
    """After its batcher round, a Count still holds its slot (result
    serialization) but must stop counting as a potential batch mate."""
    ctl = AdmissionController(max_concurrent=8)
    t = ctl.admit(batchable=True, index="i")
    assert ctl.load("i") == 1
    t.done_batching()
    assert ctl.load("i") == 0
    t.release()  # must not double-decrement
    assert ctl.load("i") == 0
    assert ctl.pending() == (0, 0)
    t2 = ctl.admit(batchable=True, index="i")
    t2.release()  # release without done_batching still decrements once
    assert ctl.load("i") == 0
    assert ctl.pending() == (0, 0)


def test_waiting_legs_are_not_barged_by_new_arrivals():
    ctl = AdmissionController(max_concurrent=1, queue_depth=4)
    l0 = ctl.admit(leg=True)
    done = []

    def leg_worker():
        t = ctl.admit(leg=True)
        done.append(t)
        t.release()

    threads = []
    for i in range(2):
        th = threading.Thread(target=leg_worker, daemon=True)
        th.start()
        threads.append(th)
        _wait_until(
            lambda n=i: ctl.pending()[0] == n + 1, what="leg waiting"
        )
    l0.release()
    for th in threads:
        th.join(5)
    assert len(done) == 2
    assert ctl.pending() == (0, 0)


def test_retry_restamps_shrunken_deadline_header():
    """A retried fan-out leg must advertise its SHRUNKEN remaining
    budget to the peer, not the original stamp — a stale header makes
    the peer queue the leg for time the sender no longer has."""
    from pilosa_tpu.server import faults as fmod
    from pilosa_tpu.server.client import InternalClient

    with ClusterHarness(
        1,
        in_memory=True,
        max_concurrent_queries=1,
        admission_queue_depth=0,
        shed_retry_after=0.4,
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        srv.api.create_index("rd")
        srv.api.create_field("rd", "f", {"type": "set"})
        srv.api.query("rd", "Set(1, f=1)")
        seen = []
        orig_qr = srv.api.query_response

        def spy(index, query, **kw):
            h = kw.get("headers")
            raw = h.get("X-Pilosa-Deadline") if h is not None else None
            if raw:
                seen.append(float(raw))
            return orig_qr(index, query, **kw)

        srv.api.query_response = spy
        # fill the LEG lane so the first internal attempt is shed 429
        blocker = srv.scheduler.admit(leg=True)
        client = InternalClient(
            retry_policy=fmod.RetryPolicy(max_attempts=2, base_backoff=0.01)
        )
        results = []
        th = threading.Thread(
            target=lambda: results.append(
                client.query_node(
                    uri, "rd", "Count(Row(f=1))", remote=True,
                    timeout=5.0, deadline=5.0,
                )
            ),
            daemon=True,
        )
        th.start()
        _wait_until(
            lambda: srv.stats.registry.snapshot().get(
                "sched.shed;class:internal,index:rd,reason:queue", 0
            )
            >= 1,
            what="first attempt shed",
        )
        blocker.release()  # retry (after Retry-After 0.4s) will succeed
        th.join(10)
        assert results and results[0] == [1]
        assert len(seen) == 2, seen
        assert seen[0] > seen[1], seen
        assert seen[1] <= seen[0] - 0.3, seen  # shrunk by >= the backoff
        assert srv.scheduler.pending() == (0, 0)


def test_invalid_default_class_rejected_at_startup():
    """A typo'd admission-default-class must fail fast, not silently
    promote all headerless traffic to interactive."""
    with pytest.raises(ValueError, match="bach"):
        AdmissionController(default_class="bach")


def test_oversized_head_drains_bytes_and_runs():
    """An over-budget query must not starve under a sustained stream of
    byte-weighted traffic: once queued, its reservation stops further
    byte grants, the account drains, and it runs."""
    ctl = AdmissionController(max_concurrent=4, byte_budget=100)
    t1 = ctl.admit(cost=QueryCost(device_bytes=30))
    big, cheap = [], []
    tb = threading.Thread(
        target=lambda: big.append(
            ctl.admit(cost=QueryCost(device_bytes=500))
        ),
        daemon=True,
    )
    tb.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="oversize queued")
    tc = threading.Thread(
        target=lambda: cheap.append(
            ctl.admit(cls="batch", cost=QueryCost(device_bytes=10))
        ),
        daemon=True,
    )
    tc.start()
    _wait_until(lambda: ctl.queue_depth() == 2, what="cheap queued")
    assert not big and not cheap  # both byte-held behind the reservation
    t1.release()  # account drains to zero: the oversize head runs FIRST
    tb.join(5)
    assert big, "oversize head must run once bytes drain"
    assert not cheap  # 500 in flight: cheap is gated behind it
    big[0].release()
    tc.join(5)
    assert cheap
    cheap[0].release()
    assert ctl.pending() == (0, 0)


def test_leg_bytes_count_against_public_budget():
    """Fan-out legs account their device bytes (public admission must
    see the real HBM pressure) without ever byte-GATING — and a leg's
    release pumps the public lane it may have been blocking."""
    ctl = AdmissionController(max_concurrent=4, byte_budget=100)
    leg = ctl.admit(leg=True, cost=QueryCost(device_bytes=80))
    assert ctl.snapshot()["inflightBytes"] == 80
    blocked = []
    th = threading.Thread(
        target=lambda: blocked.append(
            ctl.admit(cost=QueryCost(device_bytes=50))
        ),
        daemon=True,
    )
    th.start()
    _wait_until(lambda: ctl.queue_depth() == 1, what="public byte-gated")
    assert not blocked  # 80 + 50 > 100: leg bytes push back on public
    leg.release()  # frees the bytes AND pumps the public lane
    th.join(5)
    assert blocked
    blocked[0].release()
    assert ctl.pending() == (0, 0)


def test_leg_lane_sheds_unmeetable_deadline_early():
    """The leg lane — the path X-Pilosa-Deadline actually arrives on —
    must early-shed once it has learned its service rate."""
    clock = FakeClock()
    ctl = AdmissionController(max_concurrent=1, clock=clock)
    warm = ctl.admit(leg=True)
    clock.advance(1.0)
    warm.release()  # learned leg service time: ~1.0s
    filler = ctl.admit(leg=True)
    with pytest.raises(ShedError) as ei:
        ctl.admit(leg=True, deadline=0.5)  # est. wait ~1.0s > 0.5s
    assert "back of the queue" in str(ei.value)
    filler.release()
    assert ctl.pending() == (0, 0)


def test_gauges_include_leg_lane():
    """A node saturated with fan-out legs must not look idle on
    /metrics: sched.inflight/queue_depth cover both lanes."""
    st = StatsClient()
    ctl = AdmissionController(max_concurrent=2, stats=st)
    leg = ctl.admit(leg=True)
    assert st.registry.snapshot()["sched.inflight"] == 1
    leg.release()
    assert st.registry.snapshot()["sched.inflight"] == 0
    assert ctl.pending() == (0, 0)
