"""Executor integration tests (reference: executor_test.go patterns)."""

import numpy as np
import pytest

from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_TIME,
    FieldOptions,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import ExecOptions, Executor
from pilosa_tpu.exec.executor import ExecError, GroupCount, Pair, ValCount
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def hx():
    h = Holder().open()
    h.create_index("i")
    return h, Executor(h)


def q(ex, pql, index="i", **kw):
    return ex.execute(index, pql, **kw)


class TestSetRowCount:
    def test_set_and_row(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        assert q(ex, "Set(100, f=1)") == [True]
        assert q(ex, "Set(100, f=1)") == [False]  # no change
        (row,) = q(ex, "Row(f=1)")
        assert row.columns().tolist() == [100]

    def test_set_across_shards(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        cols = [3, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 7]
        for c in cols:
            q(ex, f"Set({c}, f=9)")
        (row,) = q(ex, "Row(f=9)")
        assert row.columns().tolist() == cols

    def test_count(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        for c in [1, 2, SHARD_WIDTH + 1]:
            q(ex, f"Set({c}, f=1)")
        assert q(ex, "Count(Row(f=1))") == [3]

    def test_clear(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(100, f=1)")
        assert q(ex, "Clear(100, f=1)") == [True]
        assert q(ex, "Clear(100, f=1)") == [False]
        assert q(ex, "Count(Row(f=1))") == [0]

    def test_multiple_calls_one_query(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        res = q(ex, "Set(1, f=1) Set(2, f=1) Count(Row(f=1))")
        assert res == [True, True, 2]


class TestBitmapAlgebra:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("a")
        h.index("i").create_field("b")
        for c in [1, 2, 3, SHARD_WIDTH + 1]:
            q(ex, f"Set({c}, a=1)")
        for c in [2, 3, 4]:
            q(ex, f"Set({c}, b=1)")
        return h, ex

    def test_intersect(self, data):
        _, ex = data
        (row,) = q(ex, "Intersect(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [2, 3]

    def test_union(self, data):
        _, ex = data
        (row,) = q(ex, "Union(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, 2, 3, 4, SHARD_WIDTH + 1]

    def test_difference(self, data):
        _, ex = data
        (row,) = q(ex, "Difference(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, SHARD_WIDTH + 1]

    def test_xor(self, data):
        _, ex = data
        (row,) = q(ex, "Xor(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, 4, SHARD_WIDTH + 1]

    def test_not(self, data):
        _, ex = data
        (row,) = q(ex, "Not(Row(b=1))")
        # existence = all set columns; Not(b) = exists - b
        assert row.columns().tolist() == [1, SHARD_WIDTH + 1]

    def test_count_intersect(self, data):
        _, ex = data
        assert q(ex, "Count(Intersect(Row(a=1), Row(b=1)))") == [2]

    def test_shift(self, data):
        _, ex = data
        (row,) = q(ex, "Shift(Row(b=1), n=2)")
        assert row.columns().tolist() == [4, 5, 6]

    def test_shift_across_shard_boundary(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1)")
        (row,) = q(ex, "Shift(Row(f=1), n=1)")
        assert row.columns().tolist() == [SHARD_WIDTH]

    def test_empty_intersect_error(self, data):
        _, ex = data
        with pytest.raises(ExecError):
            q(ex, "Intersect()")


class TestBSIQueries:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-1000, max=1000))
        h.index("i").create_field("f")
        self.values = {1: 10, 2: -5, 3: 100, 4: 0, SHARD_WIDTH + 2: 40}
        for col, val in self.values.items():
            q(ex, f"Set({col}, v={val})")
            q(ex, f"Set({col}, f=1)")
        return h, ex

    def test_row_gt(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v > 5)")
        assert row.columns().tolist() == [1, 3, SHARD_WIDTH + 2]

    def test_row_lt_negative(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v < 0)")
        assert row.columns().tolist() == [2]

    def test_row_eq_neq(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v == 10)")
        assert row.columns().tolist() == [1]
        (row,) = q(ex, "Row(v != 10)")
        assert row.columns().tolist() == [2, 3, 4, SHARD_WIDTH + 2]

    def test_row_neq_null(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v != null)")
        assert row.columns().tolist() == sorted(self.values)

    def test_row_between(self, data):
        _, ex = data
        (row,) = q(ex, "Row(0 <= v <= 40)")
        assert row.columns().tolist() == [1, 4, SHARD_WIDTH + 2]
        (row,) = q(ex, "Row(v >< [-5, 10])")
        assert row.columns().tolist() == [1, 2, 4]

    def test_row_saturated_ranges(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v < 2000)")  # fully encompassing -> notNull
        assert row.columns().tolist() == sorted(self.values)
        (row,) = q(ex, "Row(v > 2000)")  # out of range -> empty
        assert row.columns().tolist() == []

    def test_sum(self, data):
        _, ex = data
        (vc,) = q(ex, "Sum(field=v)")
        assert vc == ValCount(value=sum(self.values.values()), count=len(self.values))

    def test_sum_filtered(self, data):
        _, ex = data
        (vc,) = q(ex, "Sum(Row(v > 0), field=v)")
        positive = [v for v in self.values.values() if v > 0]
        assert vc == ValCount(value=sum(positive), count=len(positive))

    def test_min_max(self, data):
        _, ex = data
        assert q(ex, "Min(field=v)") == [ValCount(value=-5, count=1)]
        assert q(ex, "Max(field=v)") == [ValCount(value=100, count=1)]

    def test_min_max_filtered(self, data):
        _, ex = data
        (vc,) = q(ex, "Max(Row(v < 50), field=v)")
        assert vc == ValCount(value=40, count=1)

    def test_set_overwrite_value(self, data):
        _, ex = data
        q(ex, "Set(1, v=77)")
        (row,) = q(ex, "Row(v == 77)")
        assert row.columns().tolist() == [1]

    def test_clear_value(self, data):
        _, ex = data
        assert q(ex, "Clear(1, v=0)") == [True]
        (row,) = q(ex, "Row(v != null)")
        assert 1 not in row.columns().tolist()


class TestTopN:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        # row 1: 4 cols, row 2: 2 cols, row 3: 6 cols (across 2 shards)
        for c in [1, 2, 3, 4]:
            q(ex, f"Set({c}, f=1)")
        for c in [1, 2]:
            q(ex, f"Set({c}, f=2)")
        for c in [1, 2, 3, SHARD_WIDTH + 1, SHARD_WIDTH + 2, SHARD_WIDTH + 3]:
            q(ex, f"Set({c}, f=3)")
        return h, ex

    def test_topn(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, n=2)")
        assert pairs == [Pair(id=3, count=6), Pair(id=1, count=4)]

    def test_topn_all(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f)")
        assert pairs == [Pair(id=3, count=6), Pair(id=1, count=4), Pair(id=2, count=2)]

    def test_topn_with_src(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, Row(f=2), n=5)")
        assert pairs[0] == Pair(id=1, count=2) or pairs[0] == Pair(id=2, count=2)
        by_id = {p.id: p.count for p in pairs}
        assert by_id == {1: 2, 2: 2, 3: 2}

    def test_topn_ids(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, ids=[1, 2])")
        assert {p.id: p.count for p in pairs} == {1: 4, 2: 2}

    def test_topn_threshold(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, threshold=3)")
        assert {p.id for p in pairs} == {1, 3}

    def test_topn_int_field_error(self, hx):
        h, ex = hx
        h.index("i").create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10))
        with pytest.raises(ExecError, match="integer field"):
            q(ex, "TopN(v)")


class TestTopNFilters:
    """TopN attrName/attrValues/tanimotoThreshold parity
    (reference: executor.go:942-995, fragment.go:1570 top filter args)."""

    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        # row 1: 5 cols, row 2: 4 cols, row 3: 3 cols, row 4: 2 cols
        for rid, ncols in ((1, 5), (2, 4), (3, 3), (4, 2)):
            for c in range(ncols):
                q(ex, f"Set({c}, f={rid})")
        q(ex, 'SetRowAttrs(f, 1, cat="x", n=1)')
        q(ex, 'SetRowAttrs(f, 2, cat="y")')
        q(ex, 'SetRowAttrs(f, 3, cat="x")')
        # row 4 has no attrs
        return h, ex

    def test_attr_filter(self, data):
        _, ex = data
        (pairs,) = q(ex, 'TopN(f, attrName="cat", attrValues=["x"])')
        assert pairs == [Pair(id=1, count=5), Pair(id=3, count=3)]

    def test_attr_filter_multi_values(self, data):
        _, ex = data
        (pairs,) = q(ex, 'TopN(f, attrName="cat", attrValues=["x", "y"], n=2)')
        assert pairs == [Pair(id=1, count=5), Pair(id=2, count=4)]

    def test_attr_filter_no_match(self, data):
        _, ex = data
        (pairs,) = q(ex, 'TopN(f, attrName="cat", attrValues=["zzz"])')
        assert pairs == []

    def test_attr_filter_missing_attr_excluded(self, data):
        _, ex = data
        (pairs,) = q(ex, 'TopN(f, attrName="cat", attrValues=["x", "y"])')
        assert 4 not in {p.id for p in pairs}

    def test_attr_filter_int_value(self, data):
        _, ex = data
        (pairs,) = q(ex, 'TopN(f, attrName="n", attrValues=[1])')
        assert pairs == [Pair(id=1, count=5)]

    def test_tanimoto(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        # src = row 9: cols 0..5 (6 cols)
        for c in range(6):
            q(ex, f"Set({c}, f=9)")
        # row 1: cols 0..5 (tanimoto 100), row 2: cols 3..8 (inter 3 of 6+6:
        # ceil(300/9)=34), row 3: cols 100..105 (inter 0)
        for c in range(6):
            q(ex, f"Set({c}, f=1)")
        for c in range(3, 9):
            q(ex, f"Set({c}, f=2)")
        for c in range(100, 106):
            q(ex, f"Set({c}, f=3)")
        import math

        def naive_tan(inter, cnt, srcc):
            return math.ceil(inter * 100 / (cnt + srcc - inter)) if inter else 0

        # threshold 50: only row 1 (and row 9 itself, tanimoto 100) qualify
        (pairs,) = q(ex, "TopN(f, Row(f=9), tanimotoThreshold=50)")
        assert {p.id for p in pairs} == {1, 9}
        assert naive_tan(3, 6, 6) == 34  # row 2's coefficient
        # threshold 30: row 2 joins
        (pairs,) = q(ex, "TopN(f, Row(f=9), tanimotoThreshold=30)")
        assert {p.id for p in pairs} == {1, 2, 9}
        # row 3 never appears (no intersection)
        (pairs,) = q(ex, "TopN(f, Row(f=9), tanimotoThreshold=1)")
        assert 3 not in {p.id for p in pairs}

    def test_tanimoto_range_error(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(1, f=1)")
        with pytest.raises(ExecError, match="1 to 100"):
            q(ex, "TopN(f, Row(f=1), tanimotoThreshold=101)")


class TestTopNAdversarial:
    """Adversarial-skew cases pinning the reference's documented
    approximation contract (VERDICT r1 weak #4): the candidate pool is the
    rank cache — never a 2n heuristic — and intersection filters that
    invert cache order must still surface the true winners."""

    def test_cache_smaller_than_candidate_set(self, hx):
        """Rows evicted from a cache smaller than the row count are not
        candidates (the documented approximation; fragment.go:1570)."""
        h, ex = hx
        h.index("i").create_field(
            "f", FieldOptions(cache_type="ranked", cache_size=3)
        )
        for rid, ncols in ((1, 10), (2, 8), (3, 6), (4, 4), (5, 2)):
            for c in range(ncols):
                q(ex, f"Set({c}, f={rid})")
        (pairs,) = q(ex, "TopN(f, n=5)")
        # cache keeps the top 3 by count; evicted rows 4, 5 are invisible
        assert [p.id for p in pairs] == [1, 2, 3]

    def test_filter_inverts_cache_order(self, hx):
        """A src filter that makes a low-ranked row the true winner must
        not be trimmed away by pass 1."""
        h, ex = hx
        h.index("i").create_field("f")
        h.index("i").create_field("g")
        # row 1: 20 cols (rank 1), row 2: 6 cols (rank 2)
        for c in range(20):
            q(ex, f"Set({c}, f=1)")
        for c in range(100, 106):
            q(ex, f"Set({c}, f=2)")
        # src overlaps row 1 in 1 col, row 2 fully
        for c in [0] + list(range(100, 106)):
            q(ex, f"Set({c}, g=9)")
        (pairs,) = q(ex, "TopN(f, Row(g=9), n=1)")
        assert pairs[0].id == 2 and pairs[0].count == 6

    def test_boundary_ties_deterministic(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        # rows 1..4 all with 3 cols: tie at every boundary
        for rid in (1, 2, 3, 4):
            for c in range(3):
                q(ex, f"Set({c}, f={rid})")
        (pairs,) = q(ex, "TopN(f, n=2)")
        # deterministic: ties broken by ascending row id
        assert [p.id for p in pairs] == [1, 2]
        assert all(p.count == 3 for p in pairs)

    def test_threshold_with_src_counts(self, hx):
        """threshold applies to the FILTERED count, not the cache count."""
        h, ex = hx
        h.index("i").create_field("f")
        for c in range(10):
            q(ex, f"Set({c}, f=1)")
        for c in range(2):
            q(ex, f"Set({c}, f=9)")
        # row 1 has 10 cols but only 2 intersect src; threshold 5 drops it
        (pairs,) = q(ex, "TopN(f, Row(f=9), threshold=5)")
        assert 1 not in {p.id for p in pairs}
        (pairs,) = q(ex, "TopN(f, Row(f=9), threshold=2)")
        assert {p.id: p.count for p in pairs}[1] == 2

    def test_multishard_skew(self, hx):
        """A row dominant in one shard but absent elsewhere vs a row spread
        thin: exact second-pass re-count must rank by global count."""
        h, ex = hx
        h.index("i").create_field("f")
        # row 1: 8 cols all in shard 0; row 2: 3 cols in each of 3 shards (9)
        for c in range(8):
            q(ex, f"Set({c}, f=1)")
        for s in range(3):
            for c in range(3):
                q(ex, f"Set({s * SHARD_WIDTH + c}, f=2)")
        (pairs,) = q(ex, "TopN(f, n=1)")
        assert pairs == [Pair(id=2, count=9)]


class TestRowsGroupBy:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("a")
        h.index("i").create_field("b")
        # a rows: 0 {1,2}, 1 {2,3}; b rows: 10 {1,3}, 11 {2}
        for col, row in [(1, 0), (2, 0), (2, 1), (3, 1)]:
            q(ex, f"Set({col}, a={row})")
        for col, row in [(1, 10), (3, 10), (2, 11)]:
            q(ex, f"Set({col}, b={row})")
        return h, ex

    def test_rows(self, data):
        _, ex = data
        assert q(ex, "Rows(a)") == [[0, 1]]

    def test_rows_previous_limit(self, data):
        _, ex = data
        assert q(ex, "Rows(a, previous=0)") == [[1]]
        assert q(ex, "Rows(a, limit=1)") == [[0]]

    def test_rows_column(self, data):
        _, ex = data
        assert q(ex, "Rows(a, column=3)") == [[1]]

    def test_groupby(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), Rows(b))")
        got = {(tuple(fr.row_id for fr in g.group)): g.count for g in groups}
        # a=0 {1,2} x b=10 {1,3} -> {1}; a=0 x b=11 {2} -> {2};
        # a=1 {2,3} x b=10 -> {3}; a=1 x b=11 -> {2}
        assert got == {(0, 10): 1, (0, 11): 1, (1, 10): 1, (1, 11): 1}

    def test_groupby_filter(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), filter=Row(b=10))")
        got = {tuple(fr.row_id for fr in g.group): g.count for g in groups}
        assert got == {(0,): 1, (1,): 1}

    def test_groupby_limit(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), Rows(b), limit=2)")
        assert len(groups) == 2

    def test_groupby_invalid_child(self, data):
        _, ex = data
        with pytest.raises(ExecError, match="must be 'Rows'"):
            q(ex, "GroupBy(Row(a=0))")


class TestGroupByPrevious:
    """Pagination cursor semantics from the reference's wrapping tests
    (executor_test.go:3704-3790): resume strictly after the previous group
    in sorted cross-product order, with per-child seek/wrap behavior."""

    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        # same bits in three fields: row0 all {0,1,2}, row1 odds {1},
        # row2 evens {0,2}, row3 no overlap {3} (executor_test.go:3739-3758)
        for f in ("wa", "wb", "wc"):
            h.index("i").create_field(f)
            for col, row in [(0, 0), (1, 0), (2, 0), (1, 1), (0, 2), (2, 2), (3, 3)]:
                q(ex, f"Set({col}, {f}={row})")
        return h, ex

    @staticmethod
    def groups_of(result):
        return [
            (tuple(fr.row_id for fr in g.group), g.count) for g in result
        ]

    def test_single_child_previous(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(wa, previous=1))")
        assert self.groups_of(groups) == [((2,), 2), ((3,), 1)]

    def test_single_child_previous_limit(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(wa, previous=1), limit=1)")
        assert self.groups_of(groups) == [((2,), 2)]

    def test_wrapping_with_previous(self, data):
        """executor_test.go:3761 — seek lands on (0,0,2) inclusive."""
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(wa), Rows(wb), Rows(wc, previous=1), limit=3)")
        assert self.groups_of(groups) == [
            ((0, 0, 2), 2),
            ((0, 1, 0), 1),
            ((0, 1, 1), 1),
        ]

    def test_previous_is_last_result(self, data):
        """executor_test.go:3771 — previous names the final group."""
        _, ex = data
        (groups,) = q(
            ex,
            "GroupBy(Rows(wa, previous=3), Rows(wb, previous=3), Rows(wc, previous=3), limit=3)",
        )
        assert groups == []

    def test_wrapping_multiple(self, data):
        """executor_test.go:3779 — zero groups skipped across two wraps."""
        _, ex = data
        (groups,) = q(
            ex, "GroupBy(Rows(wa), Rows(wb, previous=2), Rows(wc, previous=2), limit=1)"
        )
        assert self.groups_of(groups) == [((1, 0, 0), 1)]

    def test_previous_list_form(self, data):
        """GroupBy-level previous=[...] resumes after that exact group."""
        _, ex = data
        (groups,) = q(
            ex, "GroupBy(Rows(wa), Rows(wb), Rows(wc), previous=[0, 1, 0], limit=2)"
        )
        assert self.groups_of(groups) == [((0, 1, 1), 1), ((0, 2, 0), 2)]

    def test_previous_missing_row_resumes_after(self, data):
        """A previous row that no longer exists: seek lands on the next row
        and deeper levels restart (the reference's ignorePrev cascade)."""
        _, ex = data
        # rows are 0..3; previous row 4/2 on wa does not change wb semantics
        (groups,) = q(
            ex, "GroupBy(Rows(wa), Rows(wb), previous=[1, 3], limit=2)"
        )
        # after (1,3): next nonzero groups are (2,0):2 then (2,2):2
        assert self.groups_of(groups) == [((2, 0), 2), ((2, 2), 2)]

    def test_previous_with_child_limit(self, data):
        """previous + limit on one child: the reference prefetches the row
        universe with previous applied BEFORE limit (executeRows), so the
        page is [2, 3], not an empty set (limit over un-seeked rows)."""
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(wa, previous=1, limit=2))")
        assert self.groups_of(groups) == [((2,), 2), ((3,), 1)]

    def test_previous_list_mismatch(self, data):
        _, ex = data
        with pytest.raises(Exception, match="mismatched lengths"):
            q(ex, "GroupBy(Rows(wa), previous=[1, 2])")

    def test_previous_not_list(self, data):
        _, ex = data
        with pytest.raises(Exception, match="must be list"):
            q(ex, "GroupBy(Rows(wa), previous=1)")


class TestStoreClearRow:
    def test_store(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        for c in [1, 2, 3]:
            q(ex, f"Set({c}, f=1)")
        assert q(ex, "Store(Row(f=1), f=9)") == [True]
        (row,) = q(ex, "Row(f=9)")
        assert row.columns().tolist() == [1, 2, 3]

    def test_store_overwrites(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(1, f=1) Set(9, f=2) Set(10, f=2)")
        q(ex, "Store(Row(f=1), f=2)")
        (row,) = q(ex, "Row(f=2)")
        assert row.columns().tolist() == [1]

    def test_clear_row(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1) Set(2, f=2)")
        assert q(ex, "ClearRow(f=1)") == [True]
        assert q(ex, "Count(Row(f=1))") == [0]
        assert q(ex, "Count(Row(f=2))") == [1]


class TestTimeQueries:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field(
            "e", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMDH")
        )
        q(ex, "Set(1, e=1, 2019-01-05T10:00)")
        q(ex, "Set(2, e=1, 2019-03-10T11:00)")
        q(ex, "Set(3, e=1, 2020-06-01T00:00)")
        return h, ex

    def test_row_no_range(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1)")
        assert row.columns().tolist() == [1, 2, 3]

    def test_row_time_range(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1, from='2019-01-01T00:00', to='2019-12-31T00:00')")
        assert row.columns().tolist() == [1, 2]

    def test_row_from_only(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1, from='2019-02-01T00:00')")
        assert row.columns().tolist() == [2, 3]

    def test_rows_time_range(self, data):
        _, ex = data
        assert q(ex, "Rows(e, from='2019-01-01T00:00', to='2019-02-01T00:00')") == [[1]]


class TestMutexBool:
    def test_mutex_field(self, hx):
        h, ex = hx
        h.index("i").create_field("m", FieldOptions(type=FIELD_TYPE_MUTEX))
        q(ex, "Set(5, m=1)")
        q(ex, "Set(5, m=2)")
        assert q(ex, "Count(Row(m=1))") == [0]
        assert q(ex, "Count(Row(m=2))") == [1]

    def test_bool_field(self, hx):
        h, ex = hx
        h.index("i").create_field("b", FieldOptions(type=FIELD_TYPE_BOOL))
        q(ex, "Set(5, b=true)")
        (row,) = q(ex, "Row(b=true)")
        assert row.columns().tolist() == [5]
        q(ex, "Set(5, b=false)")
        (row,) = q(ex, "Row(b=false)")
        assert row.columns().tolist() == [5]
        assert q(ex, "Count(Row(b=true))") == [0]


class TestAttrsOptions:
    def test_row_attrs(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        assert q(ex, 'SetRowAttrs(f, 1, label="hello", rank=5)') == [None]
        assert h.index("i").field("f").row_attr_store.attrs(1) == {
            "label": "hello",
            "rank": 5,
        }

    def test_column_attrs(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, 'SetColumnAttrs(9, name="col9")')
        assert h.index("i").column_attr_store.attrs(9) == {"name": "col9"}

    def test_attr_delete_with_null(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, 'SetRowAttrs(f, 1, x=5)')
        q(ex, 'SetRowAttrs(f, 1, x=null)')
        assert h.index("i").field("f").row_attr_store.attrs(1) == {}

    def test_options_shards(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1)")
        (row,) = q(ex, "Options(Row(f=1), shards=[0])")
        assert row.columns().tolist() == [1]


class TestResponseAttrs:
    """Attrs in query responses (reference: executor.go:113-205 Execute +
    executor.go:595-647 executeBitmapCall tail)."""

    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(1, f=1) Set(2, f=1)")
        q(ex, 'SetRowAttrs(f, 1, label="hello")')
        q(ex, 'SetColumnAttrs(1, city="austin")')
        return h, ex

    def test_row_attrs_attached(self, data):
        _, ex = data
        (row,) = q(ex, "Row(f=1)")
        assert row.attrs == {"label": "hello"}

    def test_row_without_attrs_empty(self, data):
        _, ex = data
        (row,) = q(ex, "Row(f=2)")
        assert not row.attrs

    def test_exclude_row_attrs(self, data):
        _, ex = data
        (row,) = q(ex, "Options(Row(f=1), excludeRowAttrs=true)")
        assert row.attrs == {}
        assert row.columns().tolist() == [1, 2]

    def test_exclude_columns(self, data):
        _, ex = data
        (row,) = q(ex, "Options(Row(f=1), excludeColumns=true)")
        assert row.columns().tolist() == []
        assert row.attrs == {"label": "hello"}

    def test_column_attrs_in_response(self, data):
        h, ex = data
        resp = ex.execute_response(
            "i", "Row(f=1)", opt=ExecOptions(column_attrs=True)
        )
        assert [s.to_json() for s in resp.column_attr_sets] == [
            {"id": 1, "attrs": {"city": "austin"}}
        ]

    def test_column_attrs_via_options(self, data):
        _, ex = data
        resp = ex.execute_response("i", "Options(Row(f=1), columnAttrs=true)")
        assert resp.column_attr_sets and resp.column_attr_sets[0].id == 1

    def test_no_column_attrs_by_default(self, data):
        _, ex = data
        resp = ex.execute_response("i", "Row(f=1)")
        assert resp.column_attr_sets is None

    def test_bsi_condition_row_has_no_attrs(self, hx):
        h, ex = hx
        h.index("i").create_field(
            "v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10)
        )
        q(ex, "Set(1, v=5)")
        (row,) = q(ex, "Row(v > 1)")
        assert row.attrs is None  # condition rows carry no attrs


class TestErrors:
    def test_missing_index(self, hx):
        _, ex = hx
        from pilosa_tpu.exec.executor import NotFoundError

        with pytest.raises(NotFoundError):
            ex.execute("nope", "Row(f=1)")

    def test_missing_field(self, hx):
        _, ex = hx
        from pilosa_tpu.exec.executor import NotFoundError

        with pytest.raises(NotFoundError):
            q(ex, "Row(f=1)")

    def test_count_two_children(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        with pytest.raises(ExecError):
            q(ex, "Count(Row(f=1), Row(f=2))")


class TestReviewRegressions:
    """Regressions for review-confirmed bugs."""

    def test_mutex_clear_then_set(self, hx):
        # clear paths must maintain the mutex vector
        h, ex = hx
        h.index("i").create_field("b", FieldOptions(type=FIELD_TYPE_BOOL))
        assert q(ex, "Set(5, b=true)") == [True]
        assert q(ex, "Clear(5, b=true)") == [True]
        assert q(ex, "Set(5, b=true)") == [True]  # was False before fix
        assert q(ex, "Count(Row(b=true))") == [1]

    def test_mutex_clear_row_then_set(self, hx):
        h, ex = hx
        h.index("i").create_field("m", FieldOptions(type=FIELD_TYPE_MUTEX))
        q(ex, "Set(5, m=3)")
        q(ex, "ClearRow(m=3)")
        assert q(ex, "Set(5, m=3)") == [True]
        assert q(ex, "Count(Row(m=3))") == [1]

    def test_shift_nested_in_intersect(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(10, f=1) Set(11, f=2)")
        (row,) = q(ex, "Intersect(Shift(Row(f=1), n=1), Row(f=2))")
        assert row.columns().tolist() == [11]

    def test_count_shift_across_boundary(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1) Set(1, f=1)")
        assert q(ex, "Count(Shift(Row(f=1), n=1))") == [2]

    def test_nested_double_shift(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1)")
        (row,) = q(ex, "Shift(Shift(Row(f=1), n=1), n=1)")
        assert row.columns().tolist() == [SHARD_WIDTH + 1]
