"""Executor integration tests (reference: executor_test.go patterns)."""

import numpy as np
import pytest

from pilosa_tpu.core.field import FIELD_TYPE_BOOL, FIELD_TYPE_INT, FIELD_TYPE_MUTEX, FIELD_TYPE_TIME, FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import ExecOptions, Executor
from pilosa_tpu.exec.executor import ExecError, GroupCount, Pair, ValCount
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def hx():
    h = Holder().open()
    h.create_index("i")
    return h, Executor(h)


def q(ex, pql, index="i", **kw):
    return ex.execute(index, pql, **kw)


class TestSetRowCount:
    def test_set_and_row(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        assert q(ex, "Set(100, f=1)") == [True]
        assert q(ex, "Set(100, f=1)") == [False]  # no change
        (row,) = q(ex, "Row(f=1)")
        assert row.columns().tolist() == [100]

    def test_set_across_shards(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        cols = [3, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 7]
        for c in cols:
            q(ex, f"Set({c}, f=9)")
        (row,) = q(ex, "Row(f=9)")
        assert row.columns().tolist() == cols

    def test_count(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        for c in [1, 2, SHARD_WIDTH + 1]:
            q(ex, f"Set({c}, f=1)")
        assert q(ex, "Count(Row(f=1))") == [3]

    def test_clear(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(100, f=1)")
        assert q(ex, "Clear(100, f=1)") == [True]
        assert q(ex, "Clear(100, f=1)") == [False]
        assert q(ex, "Count(Row(f=1))") == [0]

    def test_multiple_calls_one_query(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        res = q(ex, "Set(1, f=1) Set(2, f=1) Count(Row(f=1))")
        assert res == [True, True, 2]


class TestBitmapAlgebra:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("a")
        h.index("i").create_field("b")
        for c in [1, 2, 3, SHARD_WIDTH + 1]:
            q(ex, f"Set({c}, a=1)")
        for c in [2, 3, 4]:
            q(ex, f"Set({c}, b=1)")
        return h, ex

    def test_intersect(self, data):
        _, ex = data
        (row,) = q(ex, "Intersect(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [2, 3]

    def test_union(self, data):
        _, ex = data
        (row,) = q(ex, "Union(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, 2, 3, 4, SHARD_WIDTH + 1]

    def test_difference(self, data):
        _, ex = data
        (row,) = q(ex, "Difference(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, SHARD_WIDTH + 1]

    def test_xor(self, data):
        _, ex = data
        (row,) = q(ex, "Xor(Row(a=1), Row(b=1))")
        assert row.columns().tolist() == [1, 4, SHARD_WIDTH + 1]

    def test_not(self, data):
        _, ex = data
        (row,) = q(ex, "Not(Row(b=1))")
        # existence = all set columns; Not(b) = exists - b
        assert row.columns().tolist() == [1, SHARD_WIDTH + 1]

    def test_count_intersect(self, data):
        _, ex = data
        assert q(ex, "Count(Intersect(Row(a=1), Row(b=1)))") == [2]

    def test_shift(self, data):
        _, ex = data
        (row,) = q(ex, "Shift(Row(b=1), n=2)")
        assert row.columns().tolist() == [4, 5, 6]

    def test_shift_across_shard_boundary(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1)")
        (row,) = q(ex, "Shift(Row(f=1), n=1)")
        assert row.columns().tolist() == [SHARD_WIDTH]

    def test_empty_intersect_error(self, data):
        _, ex = data
        with pytest.raises(ExecError):
            q(ex, "Intersect()")


class TestBSIQueries:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-1000, max=1000))
        h.index("i").create_field("f")
        self.values = {1: 10, 2: -5, 3: 100, 4: 0, SHARD_WIDTH + 2: 40}
        for col, val in self.values.items():
            q(ex, f"Set({col}, v={val})")
            q(ex, f"Set({col}, f=1)")
        return h, ex

    def test_row_gt(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v > 5)")
        assert row.columns().tolist() == [1, 3, SHARD_WIDTH + 2]

    def test_row_lt_negative(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v < 0)")
        assert row.columns().tolist() == [2]

    def test_row_eq_neq(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v == 10)")
        assert row.columns().tolist() == [1]
        (row,) = q(ex, "Row(v != 10)")
        assert row.columns().tolist() == [2, 3, 4, SHARD_WIDTH + 2]

    def test_row_neq_null(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v != null)")
        assert row.columns().tolist() == sorted(self.values)

    def test_row_between(self, data):
        _, ex = data
        (row,) = q(ex, "Row(0 <= v <= 40)")
        assert row.columns().tolist() == [1, 4, SHARD_WIDTH + 2]
        (row,) = q(ex, "Row(v >< [-5, 10])")
        assert row.columns().tolist() == [1, 2, 4]

    def test_row_saturated_ranges(self, data):
        _, ex = data
        (row,) = q(ex, "Row(v < 2000)")  # fully encompassing -> notNull
        assert row.columns().tolist() == sorted(self.values)
        (row,) = q(ex, "Row(v > 2000)")  # out of range -> empty
        assert row.columns().tolist() == []

    def test_sum(self, data):
        _, ex = data
        (vc,) = q(ex, "Sum(field=v)")
        assert vc == ValCount(value=sum(self.values.values()), count=len(self.values))

    def test_sum_filtered(self, data):
        _, ex = data
        (vc,) = q(ex, "Sum(Row(v > 0), field=v)")
        positive = [v for v in self.values.values() if v > 0]
        assert vc == ValCount(value=sum(positive), count=len(positive))

    def test_min_max(self, data):
        _, ex = data
        assert q(ex, "Min(field=v)") == [ValCount(value=-5, count=1)]
        assert q(ex, "Max(field=v)") == [ValCount(value=100, count=1)]

    def test_min_max_filtered(self, data):
        _, ex = data
        (vc,) = q(ex, "Max(Row(v < 50), field=v)")
        assert vc == ValCount(value=40, count=1)

    def test_set_overwrite_value(self, data):
        _, ex = data
        q(ex, "Set(1, v=77)")
        (row,) = q(ex, "Row(v == 77)")
        assert row.columns().tolist() == [1]

    def test_clear_value(self, data):
        _, ex = data
        assert q(ex, "Clear(1, v=0)") == [True]
        (row,) = q(ex, "Row(v != null)")
        assert 1 not in row.columns().tolist()


class TestTopN:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        # row 1: 4 cols, row 2: 2 cols, row 3: 6 cols (across 2 shards)
        for c in [1, 2, 3, 4]:
            q(ex, f"Set({c}, f=1)")
        for c in [1, 2]:
            q(ex, f"Set({c}, f=2)")
        for c in [1, 2, 3, SHARD_WIDTH + 1, SHARD_WIDTH + 2, SHARD_WIDTH + 3]:
            q(ex, f"Set({c}, f=3)")
        return h, ex

    def test_topn(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, n=2)")
        assert pairs == [Pair(id=3, count=6), Pair(id=1, count=4)]

    def test_topn_all(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f)")
        assert pairs == [Pair(id=3, count=6), Pair(id=1, count=4), Pair(id=2, count=2)]

    def test_topn_with_src(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, Row(f=2), n=5)")
        assert pairs[0] == Pair(id=1, count=2) or pairs[0] == Pair(id=2, count=2)
        by_id = {p.id: p.count for p in pairs}
        assert by_id == {1: 2, 2: 2, 3: 2}

    def test_topn_ids(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, ids=[1, 2])")
        assert {p.id: p.count for p in pairs} == {1: 4, 2: 2}

    def test_topn_threshold(self, data):
        _, ex = data
        (pairs,) = q(ex, "TopN(f, threshold=3)")
        assert {p.id for p in pairs} == {1, 3}

    def test_topn_int_field_error(self, hx):
        h, ex = hx
        h.index("i").create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10))
        with pytest.raises(ExecError, match="integer field"):
            q(ex, "TopN(v)")


class TestRowsGroupBy:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field("a")
        h.index("i").create_field("b")
        # a rows: 0 {1,2}, 1 {2,3}; b rows: 10 {1,3}, 11 {2}
        for col, row in [(1, 0), (2, 0), (2, 1), (3, 1)]:
            q(ex, f"Set({col}, a={row})")
        for col, row in [(1, 10), (3, 10), (2, 11)]:
            q(ex, f"Set({col}, b={row})")
        return h, ex

    def test_rows(self, data):
        _, ex = data
        assert q(ex, "Rows(a)") == [[0, 1]]

    def test_rows_previous_limit(self, data):
        _, ex = data
        assert q(ex, "Rows(a, previous=0)") == [[1]]
        assert q(ex, "Rows(a, limit=1)") == [[0]]

    def test_rows_column(self, data):
        _, ex = data
        assert q(ex, "Rows(a, column=3)") == [[1]]

    def test_groupby(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), Rows(b))")
        got = {(tuple(fr.row_id for fr in g.group)): g.count for g in groups}
        # a=0 {1,2} x b=10 {1,3} -> {1}; a=0 x b=11 {2} -> {2};
        # a=1 {2,3} x b=10 -> {3}; a=1 x b=11 -> {2}
        assert got == {(0, 10): 1, (0, 11): 1, (1, 10): 1, (1, 11): 1}

    def test_groupby_filter(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), filter=Row(b=10))")
        got = {tuple(fr.row_id for fr in g.group): g.count for g in groups}
        assert got == {(0,): 1, (1,): 1}

    def test_groupby_limit(self, data):
        _, ex = data
        (groups,) = q(ex, "GroupBy(Rows(a), Rows(b), limit=2)")
        assert len(groups) == 2

    def test_groupby_invalid_child(self, data):
        _, ex = data
        with pytest.raises(ExecError, match="must be 'Rows'"):
            q(ex, "GroupBy(Row(a=0))")


class TestStoreClearRow:
    def test_store(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        for c in [1, 2, 3]:
            q(ex, f"Set({c}, f=1)")
        assert q(ex, "Store(Row(f=1), f=9)") == [True]
        (row,) = q(ex, "Row(f=9)")
        assert row.columns().tolist() == [1, 2, 3]

    def test_store_overwrites(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(1, f=1) Set(9, f=2) Set(10, f=2)")
        q(ex, "Store(Row(f=1), f=2)")
        (row,) = q(ex, "Row(f=2)")
        assert row.columns().tolist() == [1]

    def test_clear_row(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1) Set(2, f=2)")
        assert q(ex, "ClearRow(f=1)") == [True]
        assert q(ex, "Count(Row(f=1))") == [0]
        assert q(ex, "Count(Row(f=2))") == [1]


class TestTimeQueries:
    @pytest.fixture
    def data(self, hx):
        h, ex = hx
        h.index("i").create_field(
            "e", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMDH")
        )
        q(ex, "Set(1, e=1, 2019-01-05T10:00)")
        q(ex, "Set(2, e=1, 2019-03-10T11:00)")
        q(ex, "Set(3, e=1, 2020-06-01T00:00)")
        return h, ex

    def test_row_no_range(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1)")
        assert row.columns().tolist() == [1, 2, 3]

    def test_row_time_range(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1, from='2019-01-01T00:00', to='2019-12-31T00:00')")
        assert row.columns().tolist() == [1, 2]

    def test_row_from_only(self, data):
        _, ex = data
        (row,) = q(ex, "Row(e=1, from='2019-02-01T00:00')")
        assert row.columns().tolist() == [2, 3]

    def test_rows_time_range(self, data):
        _, ex = data
        assert q(ex, "Rows(e, from='2019-01-01T00:00', to='2019-02-01T00:00')") == [[1]]


class TestMutexBool:
    def test_mutex_field(self, hx):
        h, ex = hx
        h.index("i").create_field("m", FieldOptions(type=FIELD_TYPE_MUTEX))
        q(ex, "Set(5, m=1)")
        q(ex, "Set(5, m=2)")
        assert q(ex, "Count(Row(m=1))") == [0]
        assert q(ex, "Count(Row(m=2))") == [1]

    def test_bool_field(self, hx):
        h, ex = hx
        h.index("i").create_field("b", FieldOptions(type=FIELD_TYPE_BOOL))
        q(ex, "Set(5, b=true)")
        (row,) = q(ex, "Row(b=true)")
        assert row.columns().tolist() == [5]
        q(ex, "Set(5, b=false)")
        (row,) = q(ex, "Row(b=false)")
        assert row.columns().tolist() == [5]
        assert q(ex, "Count(Row(b=true))") == [0]


class TestAttrsOptions:
    def test_row_attrs(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        assert q(ex, 'SetRowAttrs(f, 1, label="hello", rank=5)') == [None]
        assert h.index("i").field("f").row_attr_store.attrs(1) == {
            "label": "hello",
            "rank": 5,
        }

    def test_column_attrs(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, 'SetColumnAttrs(9, name="col9")')
        assert h.index("i").column_attr_store.attrs(9) == {"name": "col9"}

    def test_attr_delete_with_null(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, 'SetRowAttrs(f, 1, x=5)')
        q(ex, 'SetRowAttrs(f, 1, x=null)')
        assert h.index("i").field("f").row_attr_store.attrs(1) == {}

    def test_options_shards(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set(1, f=1) Set({SHARD_WIDTH + 1}, f=1)")
        (row,) = q(ex, "Options(Row(f=1), shards=[0])")
        assert row.columns().tolist() == [1]


class TestErrors:
    def test_missing_index(self, hx):
        _, ex = hx
        from pilosa_tpu.exec.executor import NotFoundError

        with pytest.raises(NotFoundError):
            ex.execute("nope", "Row(f=1)")

    def test_missing_field(self, hx):
        _, ex = hx
        from pilosa_tpu.exec.executor import NotFoundError

        with pytest.raises(NotFoundError):
            q(ex, "Row(f=1)")

    def test_count_two_children(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        with pytest.raises(ExecError):
            q(ex, "Count(Row(f=1), Row(f=2))")


class TestReviewRegressions:
    """Regressions for review-confirmed bugs."""

    def test_mutex_clear_then_set(self, hx):
        # clear paths must maintain the mutex vector
        h, ex = hx
        h.index("i").create_field("b", FieldOptions(type=FIELD_TYPE_BOOL))
        assert q(ex, "Set(5, b=true)") == [True]
        assert q(ex, "Clear(5, b=true)") == [True]
        assert q(ex, "Set(5, b=true)") == [True]  # was False before fix
        assert q(ex, "Count(Row(b=true))") == [1]

    def test_mutex_clear_row_then_set(self, hx):
        h, ex = hx
        h.index("i").create_field("m", FieldOptions(type=FIELD_TYPE_MUTEX))
        q(ex, "Set(5, m=3)")
        q(ex, "ClearRow(m=3)")
        assert q(ex, "Set(5, m=3)") == [True]
        assert q(ex, "Count(Row(m=3))") == [1]

    def test_shift_nested_in_intersect(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, "Set(10, f=1) Set(11, f=2)")
        (row,) = q(ex, "Intersect(Shift(Row(f=1), n=1), Row(f=2))")
        assert row.columns().tolist() == [11]

    def test_count_shift_across_boundary(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1) Set(1, f=1)")
        assert q(ex, "Count(Shift(Row(f=1), n=1))") == [2]

    def test_nested_double_shift(self, hx):
        h, ex = hx
        h.index("i").create_field("f")
        q(ex, f"Set({SHARD_WIDTH - 1}, f=1)")
        (row,) = q(ex, "Shift(Shift(Row(f=1), n=1), n=1)")
        assert row.columns().tolist() == [SHARD_WIDTH + 1]
