"""Storage hierarchy tests: fragment/view/field/index/holder.

Mirrors the reference's white-box tier (fragment_internal_test.go,
field_internal_test.go, holder_internal_test.go)."""

from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_TIME,
    FieldOptions,
)
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core import timeq
from pilosa_tpu.ops import bitmap as ob
from pilosa_tpu.shardwidth import SHARD_WIDTH


def frag(path=None, **kw):
    return Fragment(path, "i", "f", "standard", 0, **kw).open()


class TestFragment:
    def test_set_clear_bit(self):
        f = frag()
        assert f.set_bit(3, 100)
        assert not f.set_bit(3, 100)
        assert f.contains(3, 100)
        assert f.row_count(3) == 1
        assert f.clear_bit(3, 100)
        assert not f.clear_bit(3, 100)
        assert f.row_count(3) == 0

    def test_absolute_and_inshard_cols(self):
        f = Fragment(None, "i", "f", "standard", 2).open()
        assert f.set_bit(1, 2 * SHARD_WIDTH + 7)  # absolute col of shard 2
        assert f.contains(1, 7)
        with pytest.raises(ValueError):
            f.set_bit(1, 5 * SHARD_WIDTH + 7)  # wrong shard

    def test_bulk_import_and_row(self, rng):
        f = frag()
        cols = np.unique(rng.integers(0, SHARD_WIDTH, 5000))
        f.bulk_import(np.full(len(cols), 7, np.uint64), cols)
        assert f.row_count(7) == len(cols)
        assert np.array_equal(f.row_positions(7), cols.astype(np.uint32))
        # device row matches host row
        dev = np.asarray(f.row_device(7))
        assert ob.unpack_positions(dev).tolist() == cols.tolist()

    def test_row_counts_batched(self, rng):
        f = frag()
        for r in range(5):
            cols = np.unique(rng.integers(0, SHARD_WIDTH, 100 * (r + 1)))
            f.bulk_import(np.full(len(cols), r, np.uint64), cols)
        counts = f.row_counts(f.row_ids())
        assert counts.tolist() == [f.row_count(r) for r in f.row_ids()]

    def test_mutex(self):
        f = frag(mutex=True)
        assert f.set_bit(1, 10)
        assert f.set_bit(2, 10)  # moves col 10 from row 1 to 2
        assert not f.contains(1, 10)
        assert f.contains(2, 10)
        assert not f.set_bit(2, 10)

    def test_bulk_set_sparse_differential(self, rng):
        """Randomized differential of the r5 batched sparse-set path
        (_bulk_set_sparse: one row-major merge per fragment) against a
        Python set model: interleaved bulk imports, single-bit writes,
        clears, duplicates, rows crossing the sparse->dense threshold,
        and exact newly-set accounting."""
        f = frag()
        model: dict = {}
        dense_row = 1  # driven across the densify threshold early
        wide = np.unique(rng.integers(0, SHARD_WIDTH, SHARD_WIDTH // 16))
        n = f.bulk_import(np.full(len(wide), dense_row, np.uint64), wide)
        model[dense_row] = set(int(c) for c in wide)
        assert n == len(model[dense_row])
        for _ in range(12):
            k = int(rng.integers(1, 3000))
            rows = rng.integers(0, 9, k).astype(np.uint64)
            cols = rng.integers(0, SHARD_WIDTH, k).astype(np.uint64)
            before = sum(len(s) for s in model.values())
            got = f.bulk_import(rows, cols)
            for r, c in zip(rows, cols):
                model.setdefault(int(r), set()).add(int(c))
            want = sum(len(s) for s in model.values()) - before
            assert got == want
            # interleave point writes and clears
            r = int(rng.integers(0, 9))
            c = int(rng.integers(0, SHARD_WIDTH))
            f.set_bit(r, c)
            model.setdefault(r, set()).add(c)
            if model.get(0):
                victim = next(iter(model[0]))
                f.clear_bit(0, victim)
                model[0].discard(victim)
        for r, bits in model.items():
            assert f.row_count(r) == len(bits), r
            assert set(f.row_positions(r).tolist()) == bits, r

    def test_mutex_bulk(self):
        f = frag(mutex=True)
        f.bulk_import(
            np.array([1, 2, 3, 2], np.uint64), np.array([5, 5, 6, 6], np.uint64)
        )
        assert not f.contains(1, 5)
        assert f.contains(2, 5)
        assert not f.contains(3, 6)
        assert f.contains(2, 6)

    def test_persistence_snapshot_and_wal(self, tmp_path):
        p = str(tmp_path / "0")
        f = Fragment(p, "i", "f", "standard", 0).open()
        f.set_bit(1, 100)
        f.set_bit(2, 200)
        f.snapshot()
        f.set_bit(3, 300)  # lives only in WAL
        f.clear_bit(1, 100)
        f.close()

        f2 = Fragment(p, "i", "f", "standard", 0).open()
        assert not f2.contains(1, 100)
        assert f2.contains(2, 200)
        assert f2.contains(3, 300)

    def test_wal_torn_tail(self, tmp_path):
        p = str(tmp_path / "0")
        f = Fragment(p, "i", "f", "standard", 0).open()
        f.set_bit(1, 100)
        f.close()
        with open(p + ".wal", "ab") as fh:
            fh.write(b"\x4c\x57\x54\x50garbage")  # torn record
        f2 = Fragment(p, "i", "f", "standard", 0).open()
        assert f2.contains(1, 100)  # clean prefix replayed

    def test_auto_snapshot_on_max_op_n(self, tmp_path):
        p = str(tmp_path / "0")
        f = Fragment(p, "i", "f", "standard", 0, max_op_n=10).open()
        cols = np.arange(50, dtype=np.uint64)
        f.bulk_import(np.zeros(50, np.uint64), cols)
        import os

        assert os.path.exists(p + ".snap")
        assert os.path.getsize(p + ".wal") == 0  # truncated after snapshot
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0).open()
        assert f2.row_count(0) == 50


class TestFragmentBSI:
    def test_value_roundtrip(self):
        f = frag()
        for col, val in [(0, 0), (1, 5), (2, -7), (100, 255)]:
            f.set_value(col, 8, val)
        for col, val in [(0, 0), (1, 5), (2, -7), (100, 255)]:
            got, exists = f.value(col, 8)
            assert exists and got == val
        assert f.value(999, 8) == (0, False)

    def test_overwrite_value(self):
        f = frag()
        f.set_value(1, 8, 200)
        f.set_value(1, 8, 3)
        assert f.value(1, 8) == (3, True)

    def test_sum_min_max(self, rng):
        f = frag()
        values = {int(c): int(v) for c, v in zip(
            rng.choice(10000, 500, replace=False), rng.integers(-100, 100, 500)
        )}
        cols = np.array(sorted(values), np.uint64)
        vals = np.array([values[c] for c in sorted(values)], np.int64)
        f.import_values(cols, vals, 8)
        s, cnt = f.sum(None, 8)
        assert (s, cnt) == (sum(values.values()), len(values))
        mn, mn_cnt = f.min(None, 8)
        assert mn == min(values.values())
        assert mn_cnt == sum(1 for v in values.values() if v == mn)
        mx, mx_cnt = f.max(None, 8)
        assert mx == max(values.values())
        assert mx_cnt == sum(1 for v in values.values() if v == mx)

    @pytest.mark.parametrize("op,pred", [
        ("eq", 5), ("neq", 5), ("lt", 0), ("lt", 10), ("lte", -3),
        ("gt", 50), ("gte", -50), ("gt", -1), ("lt", -90),
    ])
    def test_range_ops(self, rng, op, pred):
        f = frag()
        values = {int(c): int(v) for c, v in zip(
            rng.choice(5000, 300, replace=False), rng.integers(-100, 100, 300)
        )}
        cols = np.array(sorted(values), np.uint64)
        vals = np.array([values[c] for c in sorted(values)], np.int64)
        f.import_values(cols, vals, 8)
        out = set(ob.unpack_positions(np.asarray(f.range_op(op, 8, pred))).tolist())
        pyop = {
            "eq": lambda v: v == pred, "neq": lambda v: v != pred,
            "lt": lambda v: v < pred, "lte": lambda v: v <= pred,
            "gt": lambda v: v > pred, "gte": lambda v: v >= pred,
        }[op]
        assert out == {c for c, v in values.items() if pyop(v)}

    def test_range_between(self, rng):
        f = frag()
        values = {int(c): int(v) for c, v in zip(
            rng.choice(5000, 300, replace=False), rng.integers(-100, 100, 300)
        )}
        f.import_values(
            np.array(sorted(values), np.uint64),
            np.array([values[c] for c in sorted(values)], np.int64),
            8,
        )
        for lo, hi in [(-10, 10), (0, 50), (-100, -1), (20, 20)]:
            out = set(ob.unpack_positions(np.asarray(f.range_between(8, lo, hi))).tolist())
            assert out == {c for c, v in values.items() if lo <= v <= hi}


class TestTimeQuantum:
    def test_views_by_time(self):
        t = datetime(2019, 7, 4, 15, 0)
        assert timeq.views_by_time("standard", t, "YMDH") == [
            "standard_2019", "standard_201907", "standard_20190704",
            "standard_2019070415",
        ]

    def test_views_by_time_range_ymdh(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2019, 12, 31, 22, 0), datetime(2020, 1, 2, 2, 0), "YMDH"
        )
        assert views == [
            "standard_2019123122", "standard_2019123123",
            "standard_20200101",
            "standard_2020010200", "standard_2020010201",
        ]

    def test_views_by_time_range_y(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2018, 1, 1), datetime(2020, 1, 1), "Y"
        )
        assert views == ["standard_2018", "standard_2019"]

    def test_invalid_quantum(self):
        with pytest.raises(ValueError):
            timeq.validate_quantum("XZ")


class TestFieldIndexHolder:
    def test_set_field_with_time(self):
        h = Holder().open()
        idx = h.create_index("i")
        f = idx.create_field(
            "events", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD")
        )
        ts = datetime(2019, 7, 4, 15, 0)
        assert f.set_bit(1, 100, ts)
        # bit present in standard + 3 time views
        assert sorted(f.views) == [
            "standard", "standard_2019", "standard_201907", "standard_20190704",
        ]
        for v in f.views.values():
            assert v.fragment(0).contains(1, 100)

    def test_int_field_value(self):
        h = Holder().open()
        idx = h.create_index("i")
        f = idx.create_field("amount", FieldOptions(type=FIELD_TYPE_INT, min=-100, max=1000))
        assert f.options.base == 0
        assert f.set_value(5, 250)
        assert f.value(5) == (250, True)
        assert f.value(6) == (0, False)
        with pytest.raises(ValueError):
            f.set_value(1, 5000)

    def test_int_field_base_offset(self):
        h = Holder().open()
        idx = h.create_index("i")
        f = idx.create_field("year", FieldOptions(type=FIELD_TYPE_INT, min=2000, max=2100))
        assert f.options.base == 2000
        f.set_value(1, 2019)
        assert f.value(1) == (2019, True)

    def test_bool_mutex_semantics(self):
        h = Holder().open()
        idx = h.create_index("i")
        f = idx.create_field("flag", FieldOptions(type=FIELD_TYPE_BOOL))
        f.set_bit(1, 10)  # true
        f.set_bit(0, 10)  # flips to false
        std = f.view("standard")
        assert not std.fragment(0).contains(1, 10)
        assert std.fragment(0).contains(0, 10)

    def test_existence_tracking(self):
        h = Holder().open()
        idx = h.create_index("i")
        idx.create_field("f")
        idx.track_columns(np.array([1, 5, 9], np.uint64))
        ef = idx.existence_field()
        assert ef.view("standard").fragment(0).row_count(0) == 3

    def test_holder_persistence_roundtrip(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("myidx", keys=False)
        f = idx.create_field("stars", FieldOptions(cache_size=100))
        f.set_bit(10, 12345)
        fi = idx.create_field("amount", FieldOptions(type=FIELD_TYPE_INT, min=0, max=500))
        fi.set_value(3, 42)
        h.close()

        h2 = Holder(str(tmp_path)).open()
        idx2 = h2.index("myidx")
        assert idx2 is not None
        f2 = idx2.field("stars")
        assert f2.options.cache_size == 100
        assert f2.view("standard").fragment(0).contains(10, 12345)
        assert idx2.field("amount").value(3) == (42, True)
        assert idx2.field("amount").options.type == FIELD_TYPE_INT

    def test_schema(self):
        h = Holder().open()
        idx = h.create_index("i")
        idx.create_field("f")
        schema = h.schema()
        assert schema[0]["name"] == "i"
        assert schema[0]["fields"][0]["name"] == "f"

    def test_invalid_names(self):
        h = Holder().open()
        with pytest.raises(ValueError):
            h.create_index("Bad")
        idx = h.create_index("ok")
        with pytest.raises(ValueError):
            idx.create_field("_reserved")

    def test_delete(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        idx.create_field("f").set_bit(1, 1)
        idx.delete_field("f")
        assert idx.field("f") is None
        h.delete_index("i")
        assert h.index("i") is None
        import os

        assert not os.path.exists(os.path.join(str(tmp_path), "i"))


class TestImportRowWords:
    """Word-level bulk ingest (Fragment.import_row_words), the device-native
    analog of the reference's ImportRoaringBits zero-parse path
    (fragment.go:2255, roaring.go:1511)."""

    def test_union_and_counts(self, rng):
        from pilosa_tpu.core.fragment import Fragment
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        W = SHARD_WIDTH // 32
        frag = Fragment(None, "i", "f", "standard", 0).open()
        frag.set_bit(3, 5)  # pre-existing sparse bit
        words = np.zeros(W, np.uint32)
        words[0] = 0b1011  # positions 0,1,3
        added = frag.import_row_words(3, words)
        # position 5 already set; 0,1,3 are new
        assert added == 3
        assert frag.row_count(3) == 4
        assert sorted(frag.row_positions(3).tolist()) == [0, 1, 3, 5]
        # idempotent: re-import adds nothing
        assert frag.import_row_words(3, words) == 0
        # rank cache tracks the exact count
        assert dict(frag.cache_top())[3] == 4

    def test_wal_replay_roundtrip(self, tmp_path, rng):
        from pilosa_tpu.core.fragment import Fragment
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        W = SHARD_WIDTH // 32
        path = str(tmp_path / "frag")
        frag = Fragment(path, "i", "f", "standard", 0, max_op_n=10**9).open()
        words = rng.integers(0, 2**32, W, np.uint32).astype(np.uint32)
        frag.import_row_words(7, words)
        frag.set_bit(2, 9)
        want7 = frag.row_positions(7).tolist()
        # simulate crash: reopen without close/snapshot -> WAL replay
        frag2 = Fragment(path, "i", "f", "standard", 0).open()
        assert frag2.row_positions(7).tolist() == want7
        assert frag2.contains(2, 9)

    def test_rejects_mutex_and_bad_shape(self):
        import pytest as _pytest

        from pilosa_tpu.core.fragment import Fragment
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        W = SHARD_WIDTH // 32
        m = Fragment(None, "i", "f", "standard", 0, mutex=True).open()
        with _pytest.raises(ValueError):
            m.import_row_words(1, np.zeros(W, np.uint32))
        frag = Fragment(None, "i", "f", "standard", 0).open()
        with _pytest.raises(ValueError):
            frag.import_row_words(1, np.zeros(W - 1, np.uint32))

    def test_query_integration(self, rng):
        """Imported words are visible to the executor's stacked path."""
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec.executor import Executor
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        W = SHARD_WIDTH // 32
        holder = Holder(None).open()
        idx = holder.create_index("irw")
        f = idx.create_field("f")
        a = rng.integers(0, 2**32, (3, W), np.uint32).astype(np.uint32)
        b = rng.integers(0, 2**32, (3, W), np.uint32).astype(np.uint32)
        for s in range(3):
            f.import_row_words(1, s, a[s])
            f.import_row_words(2, s, b[s])
        ex = Executor(holder)
        got = ex.execute("irw", "Count(Intersect(Row(f=1), Row(f=2)))")[0]
        want = int(np.unpackbits((a & b).view(np.uint8)).sum())
        assert got == want
        holder.close()
