"""Server / cluster integration tests.

Reference test model: executor_test.go + api_test.go over
test.MustRunCluster (in-process nodes, real localhost HTTP), plus the
clustertests fault-injection pattern (node kill -> query failover)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.testing import ClusterHarness


def http_json(method, url, body=None, ctype="application/json"):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", ctype)
    with urllib.request.urlopen(req, timeout=10) as resp:
        raw = resp.read()
    return json.loads(raw) if raw and raw[:1] in (b"{", b"[") else raw


@pytest.fixture(scope="module")
def trio():
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        yield c


# ---------------------------------------------------------------------------
# single node over HTTP
# ---------------------------------------------------------------------------


def test_single_node_http_end_to_end():
    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        http_json("POST", f"{uri}/index/i1", {"options": {}})
        http_json("POST", f"{uri}/index/i1/field/f1", {"options": {"type": "set"}})
        # raw-PQL body form
        r = http_json(
            "POST", f"{uri}/index/i1/query",
            b"Set(1, f1=10) Set(2, f1=10) Set(100000000, f1=10)",
            ctype="text/plain",
        )
        assert r["results"] == [True, True, True]
        r = http_json(
            "POST", f"{uri}/index/i1/query", {"query": "Count(Row(f1=10))"}
        )
        assert r["results"] == [3]
        r = http_json("POST", f"{uri}/index/i1/query", {"query": "Row(f1=10)"})
        assert r["results"][0]["columns"] == [1, 2, 100000000]
        schema = http_json("GET", f"{uri}/schema")
        assert schema["indexes"][0]["name"] == "i1"
        assert schema["indexes"][0]["fields"][0]["name"] == "f1"
        status = http_json("GET", f"{uri}/status")
        assert status["state"] == "NORMAL"
        # bad query -> 400 with error body
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_json("POST", f"{uri}/index/i1/query", {"query": "Nope(f=1)"})
        assert ei.value.code == 400


# ---------------------------------------------------------------------------
# three nodes, replica 2
# ---------------------------------------------------------------------------


def test_ddl_broadcast(trio):
    trio[0].api.create_index("bcast")
    trio[0].api.create_field("bcast", "f", {"type": "set"})
    for s in trio.nodes:
        assert s.holder.index("bcast") is not None
        assert s.holder.index("bcast").field("f") is not None
    trio[0].api.delete_index("bcast")
    for s in trio.nodes:
        assert s.holder.index("bcast") is None


def test_distributed_import_and_query(trio):
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    api = trio[0].api
    api.create_index("dist")
    api.create_field("dist", "f", {"type": "set"})
    # 1000 bits across 100 shards on row 0 (the clustertests shape)
    cols = [(i % 100) * SHARD_WIDTH + i for i in range(1000)]
    api.import_bits("dist", "f", [0] * len(cols), cols)

    for s in trio.nodes:  # any node answers with the cluster-wide count
        (cnt,) = s.api.query("dist", "Count(Row(f=0))")
        assert cnt == 1000

    # each shard is materialized on exactly replica_n nodes
    shard_copies = 0
    for s in trio.nodes:
        idx = s.holder.index("dist")
        f = idx.field("f")
        v = f.view("standard")
        shard_copies += len(v.fragments) if v else 0
    assert shard_copies == 100 * 2


def test_distributed_set_and_topn(trio):
    api = trio[1].api  # drive from a non-coordinator node
    api.create_index("q")
    api.create_field("q", "f", {"type": "set"})
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    far = 7 * SHARD_WIDTH + 123
    (r1,) = api.query("q", f"Set({far}, f=5)")
    assert r1 is True
    (r2,) = api.query("q", "Set(1, f=5) Set(2, f=5) Set(1, f=9)")[0:1]
    for s in trio.nodes:
        (cnt,) = s.api.query("q", "Count(Row(f=5))")
        assert cnt == 3, s.node.id
    (pairs,) = trio[2].api.query("q", "TopN(f, n=2)")
    assert [(p.id, p.count) for p in pairs] == [(5, 3), (9, 1)]


def test_distributed_keys(trio):
    api = trio[0].api
    api.create_index("keyed", keys=True)
    api.create_field("keyed", "color", {"type": "set", "keys": True})
    api.query("keyed", 'Set("alice", color="red")')
    api.query("keyed", 'Set("bob", color="red")')
    (row,) = trio[1].api.query("keyed", 'Row(color="red")')
    # node1 did not translate: key data lives on the coordinator's stores…
    # …but the query was driven through node1's executor with node1's stores.
    # Each node owns its own translation (static mesh: same writes reach all
    # nodes' stores through the routed Set calls only when node owns shard).
    assert row.count() == 2


def test_distributed_bsi_sum(trio):
    api = trio[0].api
    api.create_index("bsi")
    api.create_field("bsi", "amount", {"type": "int", "min": 0, "max": 100000})
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    cols = [5, SHARD_WIDTH + 9, 3 * SHARD_WIDTH + 2]
    vals = [100, 250, 37]
    api.import_values("bsi", "amount", cols, vals)
    (vc,) = trio[2].api.query("bsi", "Sum(field=amount)")
    assert (vc.value, vc.count) == (387, 3)
    (row,) = trio[1].api.query("bsi", "Row(amount > 99)")
    assert row.count() == 2


def test_query_failover_after_node_down():
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("ha")
        api.create_field("ha", "f", {"type": "set"})
        cols = [(i % 20) * SHARD_WIDTH + i for i in range(200)]
        api.import_bits("ha", "f", [0] * len(cols), cols)
        (cnt,) = api.query("ha", "Count(Row(f=0))")
        assert cnt == 200

        c.stop_node(2)  # fault injection: hard-stop a replica-owning node
        (cnt,) = c[0].api.query("ha", "Count(Row(f=0))")
        assert cnt == 200
        (cnt,) = c[1].api.query("ha", "Count(Row(f=0))")
        assert cnt == 200


def test_clearrow_reaches_all_replicas():
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("cr")
        api.create_field("cr", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 7 for s in range(12)]
        api.import_bits("cr", "f", [3] * len(cols), cols)
        (cleared,) = api.query("cr", "ClearRow(f=3)")
        assert cleared is True
        # EVERY node's local copy must be empty (no replica kept the row)
        for s in c.nodes:
            (cnt,) = s.api.query("cr", "Count(Row(f=3))", remote=True)
            assert cnt == 0, s.node.id
        # …so anti-entropy cannot resurrect the cleared bits
        for s in c.nodes:
            s.sync_holder()
        (cnt,) = c[1].api.query("cr", "Count(Row(f=3))")
        assert cnt == 0


def test_anti_entropy_repairs_drift():
    with ClusterHarness(2, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("ae")
        api.create_field("ae", "f", {"type": "set"})
        api.import_bits("ae", "f", [0, 0, 1], [1, 2, 3])

        # inject drift: silently add a bit on node1 only (local_only import)
        c[1].api.import_bits("ae", "f", [0], [999], local_only=True)
        n0 = c[0].api.query("ae", "Count(Row(f=0))", remote=True)[0]
        n1 = c[1].api.query("ae", "Count(Row(f=0))", remote=True)[0]
        assert (n0, n1) == (2, 3)

        # both nodes run their primary-driven sync pass
        c[0].sync_holder()
        c[1].sync_holder()
        n0 = c[0].api.query("ae", "Count(Row(f=0))", remote=True)[0]
        n1 = c[1].api.query("ae", "Count(Row(f=0))", remote=True)[0]
        # majority of 2 replicas = 1 vote -> union: both converge to 3
        assert (n0, n1) == (3, 3)


def test_probe_peers_marks_down():
    with ClusterHarness(2, in_memory=True) as c:
        assert c[0].probe_peers() == {"node0": True, "node1": True}
        c.stop_node(1)
        alive = c[0].probe_peers()
        assert alive["node1"] is False
        assert c[0].cluster.node_by_id("node1").state == "DOWN"


def test_resize_add_node():
    from pilosa_tpu.cluster.topology import Node
    from pilosa_tpu.server.node import NodeServer
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(2, replica_n=1, in_memory=True) as c:
        api = c[0].api
        api.create_index("grow")
        api.create_field("grow", "f", {"type": "set"})
        cols = [(i % 16) * SHARD_WIDTH + i for i in range(160)]
        api.import_bits("grow", "f", [0] * len(cols), cols)

        # boot a third node and stream its fragments over
        n2 = NodeServer(None, "node2").start()
        try:
            members = [
                Node(id=s.node.id, uri=s.node.uri) for s in [c[0], c[1], n2]
            ]
            # new node needs the schema before it can receive fragments
            n2.api.apply_schema(c[0].api.schema())
            old_members = [Node(id=s.node.id, uri=s.node.uri) for s in [c[0], c[1]]]
            fetched = n2.resize_to(members, old_nodes=old_members)
            assert fetched > 0
            c[0].resize_to(members)
            c[1].resize_to(members)
            # announce availability to the new node by re-syncing topology
            for s in [c[0], c[1], n2]:
                (cnt,) = s.api.query("grow", "Count(Row(f=0))")
                assert cnt == 160, s.node.id
        finally:
            n2.stop()


# ---------------------------------------------------------------------------
# roaring interchange over HTTP (api.go:368 ImportRoaring analog)
# ---------------------------------------------------------------------------


def test_query_response_attrs_http():
    """columnAttrs / excludeRowAttrs / excludeColumns over HTTP
    (reference: http/handler.go handlePostQuery option params)."""
    with ClusterHarness(1, in_memory=True) as harness:
        uri = harness[0].node.uri
        http_json("POST", f"{uri}/index/qa", {"options": {}})
        http_json("POST", f"{uri}/index/qa/field/qf", {"options": {"type": "set"}})
        http_json("POST", f"{uri}/index/qa/query", {"query": "Set(1, qf=1)"})
        http_json(
            "POST", f"{uri}/index/qa/query",
            {"query": 'SetRowAttrs(qf, 1, tag="t1") SetColumnAttrs(1, c="x")'},
        )
        r = http_json(
            "POST", f"{uri}/index/qa/query",
            {"query": "Row(qf=1)", "columnAttrs": True},
        )
        assert r["results"][0]["attrs"] == {"tag": "t1"}
        assert r["columnAttrs"] == [{"id": 1, "attrs": {"c": "x"}}]
        r = http_json(
            "POST", f"{uri}/index/qa/query",
            {"query": "Row(qf=1)", "excludeRowAttrs": True, "excludeColumns": True},
        )
        assert r["results"][0] == {"attrs": {}, "columns": []}


def test_import_export_roaring_http():
    from pilosa_tpu.core import roaring_io
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        http_json("POST", f"{uri}/index/ri", {"options": {}})
        http_json("POST", f"{uri}/index/ri/field/rf", {"options": {"type": "set"}})
        # rows 0 and 3, various cols, shard 2
        pos = np.array(
            [0 * SHARD_WIDTH + 5, 0 * SHARD_WIDTH + 9, 3 * SHARD_WIDTH + 5],
            dtype=np.uint64,
        )
        body = roaring_io.encode(pos)
        r = http_json("POST", f"{uri}/index/ri/field/rf/import-roaring/2", body,
                      ctype="application/octet-stream")
        assert r["changed"] == 3
        base = 2 * SHARD_WIDTH
        r = http_json("POST", f"{uri}/index/ri/query", {"query": "Row(rf=0)"})
        assert r["results"][0]["columns"] == [base + 5, base + 9]
        r = http_json("POST", f"{uri}/index/ri/query", {"query": "Count(Row(rf=3))"})
        assert r["results"] == [1]
        # export round-trips
        raw = http_json("GET", f"{uri}/index/ri/field/rf/export-roaring/2")
        np.testing.assert_array_equal(roaring_io.decode(raw), pos)
        # clear=1 removes bits
        clear_body = roaring_io.encode(pos[:1])
        http_json(
            "POST",
            f"{uri}/index/ri/field/rf/import-roaring/2?clear=1",
            clear_body,
            ctype="application/octet-stream",
        )
        r = http_json("POST", f"{uri}/index/ri/query", {"query": "Row(rf=0)"})
        assert r["results"][0]["columns"] == [base + 9]


def test_import_roaring_replicates(trio):
    from pilosa_tpu.core import roaring_io
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    uri = trio[0].node.uri
    http_json("POST", f"{uri}/index/rrep", {"options": {}})
    http_json("POST", f"{uri}/index/rrep/field/rrf", {"options": {"type": "set"}})
    pos = np.arange(100, dtype=np.uint64)  # row 0, cols 0..99, shard 7
    body = roaring_io.encode(pos)
    http_json("POST", f"{uri}/index/rrep/field/rrf/import-roaring/7", body,
              ctype="application/octet-stream")
    # both replicas hold the fragment locally
    owners = trio[0].cluster.shard_nodes("rrep", 7)
    held = 0
    for srv in trio.nodes:
        if srv.node.id not in {n.id for n in owners}:
            continue
        f = srv.holder.index("rrep").field("rrf")
        v = f.view()
        frag = v.fragment_if_exists(7) if v else None
        if frag is not None and frag.row_count(0) == 100:
            held += 1
    assert held == len(owners) == 2
    # and any node answers the query
    for srv in trio.nodes:
        r = http_json(
            "POST", f"{srv.node.uri}/index/rrep/query",
            {"query": "Count(Row(rrf=0))"},
        )
        assert r["results"] == [100]


def test_import_roaring_rejects_mutex_and_int():
    from pilosa_tpu.core import roaring_io

    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        http_json("POST", f"{uri}/index/mi", {"options": {}})
        http_json("POST", f"{uri}/index/mi/field/mf", {"options": {"type": "mutex"}})
        http_json(
            "POST", f"{uri}/index/mi/field/if",
            {"options": {"type": "int", "min": 0, "max": 100}},
        )
        body = roaring_io.encode(np.array([1, 2], dtype=np.uint64))
        for fname in ("mf", "if"):
            try:
                http_json(
                    "POST", f"{uri}/index/mi/field/{fname}/import-roaring/0",
                    body, ctype="application/octet-stream",
                )
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400


def test_import_roaring_rejects_bad_view_name():
    from pilosa_tpu.core import roaring_io

    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        http_json("POST", f"{uri}/index/vv", {"options": {}})
        http_json("POST", f"{uri}/index/vv/field/vf", {"options": {"type": "set"}})
        body = roaring_io.encode(np.array([1], dtype=np.uint64))
        for bad in ("..%2F..%2Fpwn", "%2Ftmp%2Fpwn", "a%2Fb"):
            try:
                http_json(
                    "POST",
                    f"{uri}/index/vv/field/vf/import-roaring/0?view={bad}",
                    body, ctype="application/octet-stream",
                )
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400


def test_anti_entropy_syncs_attrs():
    """Attr drift repairs via block-diff pull-merge (holder.go:975-1019):
    a node that missed attr broadcasts converges on the next AE pass."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("at")
        api.create_field("at", "f", {"type": "set"})
        # write attrs ONLY to node0's stores (simulating missed broadcasts)
        idx0 = c[0].holder.index("at")
        idx0.field("f").row_attr_store.set_attrs(3, {"label": "three"})
        idx0.column_attr_store.set_attrs(700, {"city": "x"})
        idx1 = c[1].holder.index("at")
        assert idx1.field("f").row_attr_store.attrs(3) == {}
        c[1].sync_holder()  # node1 pulls the drifted blocks
        assert idx1.field("f").row_attr_store.attrs(3) == {"label": "three"}
        assert idx1.column_attr_store.attrs(700) == {"city": "x"}
        # bilateral drift converges too (disjoint ids)
        idx1.field("f").row_attr_store.set_attrs(9, {"label": "nine"})
        c[0].sync_holder()
        assert idx0.field("f").row_attr_store.attrs(9) == {"label": "nine"}


def test_ae_prioritizes_mutated_fragments():
    """Fragments mutated since their last sync pass sort first in the AE
    work list; clean ones trail."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with ClusterHarness(2, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("pr")
        api.create_field("pr", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 1 for s in range(6)]
        api.import_bits("pr", "f", [0] * len(cols), cols)
        c[0].sync_holder()  # records versions for all primary-owned frags
        tasks = c[0]._ae_tasks()
        assert tasks, "node0 primary-owns nothing? test setup broke"
        # everything clean: all priorities equal; now mutate ONE shard
        target = tasks[-1][3]
        api.import_bits("pr", "f", [1], [target * SHARD_WIDTH + 9])
        reordered = c[0]._ae_tasks()
        assert reordered[0][3] == target, [t[3] for t in reordered]


def test_reference_route_parity():
    """Routes the reference serves that rounds 1-2 lacked: home, version,
    info, index listing/info, set-coordinator, fragment nodes, and
    remote-available-shards deletion."""
    with ClusterHarness(2, in_memory=True) as c:
        uri = c[0].node.uri
        assert http_json("GET", f"{uri}/")["name"] == "pilosa-tpu"
        assert http_json("GET", f"{uri}/version")["version"]
        info = http_json("GET", f"{uri}/info")
        from pilosa_tpu.shardwidth import SHARD_WIDTH

        assert info["shardWidth"] == SHARD_WIDTH
        c[0].api.create_index("ri")
        c[0].api.create_field("ri", "f", {"type": "set"})
        idxs = http_json("GET", f"{uri}/index")
        assert any(i["name"] == "ri" for i in idxs)
        one = http_json("GET", f"{uri}/index/ri")
        assert one["fields"] == ["f"] and one["shardWidth"] == SHARD_WIDTH
        owners = http_json("GET", f"{uri}/internal/fragment/nodes?index=ri&shard=0")
        assert len(owners) == 1 and owners[0]["id"] in ("node0", "node1")
        nodes = http_json("GET", f"{uri}/internal/nodes")
        assert {n["id"] for n in nodes} == {"node0", "node1"}
        # set-coordinator transfers the role everywhere
        http_json("POST", f"{uri}/cluster/resize/set-coordinator", {"id": "node1"})
        for s in c.nodes:
            coord = s.cluster.coordinator()
            assert coord is not None and coord.id == "node1", s.node.id
        # remote-available-shards delete
        f = c[0].holder.index("ri").field("f")
        f.add_remote_available([7])
        http_json(
            "DELETE", f"{uri}/internal/index/ri/field/f/remote-available-shards/7"
        )
        assert 7 not in f.remote_available_shards


def test_bad_numeric_query_params_return_400_json():
    """Satellite: malformed numeric params must be client errors with a
    JSON body naming the parameter, never opaque coercion messages."""
    import urllib.error

    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        c[0].api.create_index("qp")
        c[0].api.create_field("qp", "f", {"type": "set"})

        def expect_400(url):
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_json("GET", url)
            assert ei.value.code == 400
            body = json.loads(ei.value.read())
            ei.value.close()
            return body["error"]

        msg = expect_400(f"{uri}/index/qp/shard-nodes?shard=abc")
        assert "shard" in msg and "abc" in msg
        msg = expect_400(f"{uri}/index/qp/shard-nodes")
        assert "shard" in msg and "missing" in msg
        msg = expect_400(f"{uri}/internal/fragment/nodes?index=qp&shard=xyz")
        assert "shard" in msg
        msg = expect_400(
            f"{uri}/internal/fragment/block/data"
            "?index=qp&field=f&shard=0&block=nope"
        )
        assert "block" in msg
        msg = expect_400(f"{uri}/export?index=qp&field=f&shard=1.5")
        assert "shard" in msg
        # text-path shards list on the query route; empty segments are
        # typos that must 400, not silently drop
        for bad in ("1,two", "1,,2", ","):
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_json(
                    "POST", f"{uri}/index/qp/query?shards={bad}",
                    b"Count(Row(f=1))", ctype="text/plain",
                )
            assert ei.value.code == 400, bad
            assert "shards" in json.loads(ei.value.read())["error"]
            ei.value.close()


def test_devcache_counters_exported_on_metrics_and_debug_vars():
    """Satellite: device-cache residency counters must appear as gauges
    in the Prometheus text and /debug/vars (regression test)."""
    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        c[0].api.create_index("dm")
        c[0].api.create_field("dm", "f", {"type": "set"})
        c[0].api.query("dm", "Set(1, f=1) Set(2, f=1)")
        c[0].api.query("dm", "Count(Row(f=1))")  # touches the devcache
        text = http_json("GET", f"{uri}/metrics").decode()
        for name in (
            "pilosa_tpu_devcache_resident_bytes",
            "pilosa_tpu_devcache_entries",
            "pilosa_tpu_devcache_evictions",
            "pilosa_tpu_devcache_hits",
            "pilosa_tpu_devcache_misses",
        ):
            assert f"# TYPE {name} gauge" in text, name
            assert f"\n{name} " in text, name
        dbg = http_json("GET", f"{uri}/debug/vars")
        for key in (
            "devcache.resident_bytes",
            "devcache.entries",
            "devcache.evictions",
            "devcache.hits",
            "devcache.misses",
        ):
            assert key in dbg, key
        # a query ran: the cache saw at least one lookup
        assert dbg["devcache.hits"] + dbg["devcache.misses"] > 0


def test_import_rejects_oversized_write_request():
    """max-writes-per-request (cli/config.py) is enforced at the API
    import boundary: an oversized request is a 400-class ApiError, not
    a pool-hogging mega-import. Internal replica frames (local_only)
    are slices of an already-capped request and stay exempt."""
    from pilosa_tpu.server.api import ApiError

    from pilosa_tpu.server.node import NodeServer

    srv = NodeServer(None, "maxwrites", max_writes_per_request=8)
    try:
        srv.api.create_index("mw")
        srv.api.create_field("mw", "f", {"type": "set"})
        cols = list(range(9))
        with pytest.raises(ApiError, match="max-writes-per-request"):
            srv.api.import_bits("mw", "f", [0] * 9, cols)
        with pytest.raises(ApiError, match="max-writes-per-request"):
            srv.api.import_values("mw", "f", cols, list(range(9)))
        # at the cap is fine; the internal replica path ignores the cap
        srv.api.import_bits("mw", "f", [0] * 8, cols[:8])
        srv.api.import_bits("mw", "f", [0] * 9, cols, local_only=True)
        (cnt,) = srv.api.query("mw", "Count(Row(f=0))")
        assert cnt == 9
    finally:
        srv.stop()
