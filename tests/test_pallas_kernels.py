"""Pallas kernels vs the jnp reference paths (differential, CPU interpret).

Mirrors the reference's differential testing discipline (roaring vs naive
model, roaring/fuzzer.go): every kernel must agree bit-for-bit with the
ops/bitmap.py / ops/bsi.py implementations it can replace.
"""

import numpy as np
import pytest

import pilosa_tpu.ops.bitmap as ob
import pilosa_tpu.ops.bsi as bsi
import pilosa_tpu.ops.pallas_kernels as pk
from pilosa_tpu.shardwidth import WORDS_PER_ROW


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(42)


def rand_words(rng, *shape):
    return rng.integers(0, 2**32, size=shape, dtype=np.uint32)


@pytest.mark.parametrize("shape", [(WORDS_PER_ROW,), (3, 1024), (2, 5, 256)])
def test_count2_ops(rng, shape):
    a = rand_words(rng, *shape)
    b = rand_words(rng, *shape)
    assert int(pk.count_and(a, b)) == int(ob.count_and(a, b))
    assert int(pk.count_andnot(a, b)) == int(ob.count_andnot(a, b))
    assert int(pk.count_or(a, b)) == int(ob.popcount(np.bitwise_or(a, b)))
    assert int(pk.count_xor(a, b)) == int(ob.popcount(np.bitwise_xor(a, b)))
    assert int(pk.popcount(a)) == int(ob.popcount(a))


def test_count2_unaligned_tail(rng):
    # shapes that don't divide the tile: zero-padding must not change counts
    a = rand_words(rng, 7, 131)  # 917 words
    b = rand_words(rng, 7, 131)
    assert int(pk.count_and(a, b)) == int(ob.count_and(a, b))
    assert int(pk.popcount(a)) == int(ob.popcount(a))


def test_rows_counts(rng):
    stack = rand_words(rng, 13, 1024)  # 13 rows: exercises row padding
    filt = rand_words(rng, 1024)
    np.testing.assert_array_equal(
        np.asarray(pk.popcount_rows(stack)), np.asarray(ob.popcount_rows(stack))
    )
    np.testing.assert_array_equal(
        np.asarray(pk.count_and_rows(stack, filt)),
        np.asarray(ob.count_and_rows(stack, filt)),
    )


def test_bsi_sum_counts(rng):
    depth = 9
    w = 3000  # not a multiple of the BSI tile: exercises lane padding
    planes = rand_words(rng, depth, w)
    exists = rand_words(rng, w)
    sign = rand_words(rng, w)
    filt = rand_words(rng, w)
    c0, p0, n0 = bsi.sum_counts(planes, exists, sign, filt, depth)
    c1, p1, n1 = pk.sum_counts(planes, exists, sign, filt, depth)
    assert int(c0) == int(c1)
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    np.testing.assert_array_equal(np.asarray(n0), np.asarray(n1))


def test_bsi_sum_no_filter(rng):
    depth = 4
    w = pk._BSI_TILE  # exactly one tile
    planes = rand_words(rng, depth, w)
    exists = rand_words(rng, w)
    sign = np.zeros(w, dtype=np.uint32)
    filt = np.full(w, 0xFFFFFFFF, dtype=np.uint32)
    c0, p0, n0 = bsi.sum_counts(planes, exists, sign, filt, depth)
    c1, p1, n1 = pk.sum_counts(planes, exists, sign, filt, depth)
    assert int(c0) == int(c1)
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    assert int(np.asarray(n1).sum()) == 0


def test_bitmap_dispatch_flag(monkeypatch, rng):
    """PILOSA_TPU_PALLAS=1 routes ops.bitmap's counting ops through pallas."""
    import pilosa_tpu.ops.bitmap as bitmap

    a = rand_words(rng, 4, 256)
    b = rand_words(rng, 4, 256)
    want = int(bitmap.count_and(a, b))
    monkeypatch.setattr(bitmap, "_USE_PALLAS", True)
    assert int(bitmap.count_and(a, b)) == want
    assert int(bitmap.count_andnot(a, b)) == int(pk.count_andnot(a, b))
    np.testing.assert_array_equal(
        np.asarray(bitmap.popcount_rows(a)), np.asarray(pk.popcount_rows(a))
    )
    filt = rand_words(rng, 256)
    np.testing.assert_array_equal(
        np.asarray(bitmap.count_and_rows(a, filt)),
        np.asarray(pk.count_and_rows(a, filt)),
    )
