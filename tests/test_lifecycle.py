"""Cluster lifecycle: coordinator-driven join/remove resize jobs and a
subprocess-level fault-injection E2E (SIGKILL mid-import, WAL replay,
anti-entropy convergence).

Reference parity: cluster.go:1141-1561 (listenForJoins -> resizeJob with
RUNNING/DONE/ABORTED states + abort), api.go:1226-1250 (RemoveNode /
ResizeAbort), internal/clustertests/cluster_test.go:28-79 (containerized
kill-a-node-mid-import E2E — here OS processes instead of containers)."""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def http_json(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def wait_job(uri, want="DONE", timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = http_json("GET", f"{uri}/cluster/resize/job")
        if job["state"] != "RUNNING":
            assert job["state"] == want, job
            return job
        time.sleep(0.05)
    raise AssertionError("resize job did not finish")


# ---------------------------------------------------------------------------
# in-process join / remove / abort
# ---------------------------------------------------------------------------


def test_join_via_coordinator():
    """POST /cluster/join on the coordinator moves data to the new node and
    installs the grown topology everywhere."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("j")
        api.create_field("j", "f", {"type": "set"})
        cols = [(i % 16) * SHARD_WIDTH + i for i in range(160)]
        api.import_bits("j", "f", [0] * len(cols), cols)

        joiner = NodeServer(None, "joiner").start()
        try:
            uri = c[0].node.uri
            job = http_json(
                "POST", f"{uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            assert job["state"] in ("RUNNING", "DONE")
            wait_job(uri)
            # every node (incl. joiner) has the 3-node topology + NORMAL
            for s in [c[0], c[1], joiner]:
                assert len(s.cluster.nodes) == 3, s.node.id
                assert s.state == "NORMAL"
                (cnt,) = s.api.query("j", "Count(Row(f=0))")
                assert cnt == 160, s.node.id
            # joiner actually owns (and serves) some fragments
            assert any(
                s == joiner.node.id
                for sh in range(16)
                for s in [n.id for n in c[0].cluster.shard_nodes("j", sh)]
            )
        finally:
            joiner.stop()


def test_join_idempotent_and_gated():
    with ClusterHarness(2, in_memory=True) as c:
        uri = c[0].node.uri
        # re-join of an existing member is a no-op
        job = http_json(
            "POST", f"{uri}/cluster/join",
            {"id": c[1].node.id, "uri": c[1].node.uri},
        )
        assert job["action"] == "noop"
        # non-coordinator refuses
        with pytest.raises(urllib.error.HTTPError):
            http_json(
                "POST", f"{c[1].node.uri}/cluster/join",
                {"id": "x", "uri": "http://localhost:1"},
            )


def test_remove_node_rebalances():
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("rm")
        api.create_field("rm", "f", {"type": "set"})
        cols = [(i % 8) * SHARD_WIDTH + i for i in range(80)]
        api.import_bits("rm", "f", [0] * len(cols), cols)
        uri = c[0].node.uri
        http_json(
            "POST", f"{uri}/cluster/resize/remove-node", {"id": c[2].node.id}
        )
        wait_job(uri)
        for s in [c[0], c[1]]:
            assert len(s.cluster.nodes) == 2
            (cnt,) = s.api.query("rm", "Count(Row(f=0))")
            assert cnt == 80, s.node.id
        # the removed node unfroze (got the final status) and knows it is
        # no longer a member
        assert c[2].state == "NORMAL"
        assert all(n.id != c[2].node.id for n in c[2].cluster.nodes)


def test_remove_coordinator_transfers_role():
    with ClusterHarness(3, in_memory=True) as c:
        uri = c[0].node.uri
        http_json(
            "POST", f"{uri}/cluster/resize/remove-node", {"id": c[0].node.id}
        )
        wait_job(uri)
        # a surviving node holds coordinatorship; lifecycle ops still work
        coords = [n for n in c[1].cluster.nodes if n.is_coordinator]
        assert len(coords) == 1
        new_coord = next(s for s in [c[1], c[2]] if s.node.id == coords[0].id)
        assert new_coord.node.is_coordinator
        job = new_coord.api.resize_job()
        assert job["state"] in ("NONE", "DONE")


def test_joiner_does_not_become_coordinator():
    with ClusterHarness(2, in_memory=True) as c:
        joiner = NodeServer(None, "aaa-joiner").start()  # id sorts first
        try:
            uri = c[0].node.uri
            http_json(
                "POST", f"{uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri,
                 "isCoordinator": True},  # self-reported flag is ignored
            )
            wait_job(uri)
            coords = [n for n in c[0].cluster.nodes if n.is_coordinator]
            assert [n.id for n in coords] == [c[0].node.id]
            assert not joiner.node.is_coordinator
        finally:
            joiner.stop()


def test_join_unreachable_member_aborts_and_rolls_back():
    """A resize step failing (member down) ABORTs the job and restores the
    old topology on the surviving members."""
    with ClusterHarness(3, in_memory=True) as c:
        uri = c[0].node.uri
        old_ids = {n.id for n in c[0].cluster.nodes}
        c[2].stop()  # kill a member; its resize step will fail
        joiner = NodeServer(None, "joiner2").start()
        try:
            http_json(
                "POST", f"{uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(uri, want="ABORTED")
            assert job["error"]
            for s in [c[0], c[1]]:
                assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
                assert s.state == "NORMAL"
            # the joiner is reset to a standalone cluster, not left with a
            # divergent membership view
            assert [n.id for n in joiner.cluster.nodes] == [joiner.node.id]
            assert joiner.state == "NORMAL"
        finally:
            joiner.stop()


def test_abort_with_no_job():
    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        assert http_json("POST", f"{uri}/cluster/resize/abort")["state"] in (
            "NONE", "DONE", "ABORTED",
        )
        assert http_json("GET", f"{uri}/cluster/resize/job")["state"] == "NONE"


# ---------------------------------------------------------------------------
# subprocess E2E: SIGKILL mid-import -> restart -> WAL replay + AE converge
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_node(base, name, port, hosts, replicas=2):
    """Boot `pilosa-tpu server` as a real OS process (CPU-only env)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    args = [
        sys.executable, "-m", "pilosa_tpu.cli", "server",
        "--data-dir", os.path.join(base, name),
        "--bind", f"localhost:{port}",
        "--node-id", name,
        "--cluster-hosts", hosts,
        "--replicas", str(replicas),
        "--anti-entropy-interval", "0",
    ]
    return subprocess.Popen(
        args, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
    )


def _wait_up(uri, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            return http_json("GET", f"{uri}/status", timeout=2)
        except Exception:
            time.sleep(0.2)
    raise AssertionError(f"node at {uri} did not come up")


@pytest.mark.slow
def test_sigkill_mid_import_wal_replay_and_ae():
    """Boot 3 server processes, import across shards, SIGKILL one
    mid-import, restart it, and assert WAL replay + anti-entropy converge
    every node to the correct counts (clustertests cluster_test.go:28-79,
    with SIGKILL in place of pumba pause)."""
    base = tempfile.mkdtemp(prefix="pilosa-e2e-")
    ports = [_free_port() for _ in range(3)]
    names = ["p0", "p1", "p2"]
    hosts = ",".join(
        f"{n}@http://localhost:{p}" for n, p in zip(names, ports)
    )
    uris = [f"http://localhost:{p}" for p in ports]
    procs = [_spawn_node(base, n, p, hosts) for n, p in zip(names, ports)]
    try:
        for u in uris:
            _wait_up(u)
        http_json("POST", f"{uris[0]}/index/e2e", {"options": {}})
        http_json(
            "POST", f"{uris[0]}/index/e2e/field/f", {"options": {"type": "set"}}
        )
        rng = np.random.default_rng(11)
        all_cols = sorted(
            {int(c) for c in rng.integers(0, 8 * SHARD_WIDTH, 1000)}
        )
        half = len(all_cols) // 2
        # first half of the import lands while all nodes are alive
        http_json(
            "POST", f"{uris[0]}/index/e2e/field/f/import",
            {"rows": [0] * half, "cols": all_cols[:half]},
            timeout=120,
        )
        # attrs written pre-kill: the append-log (r5) must survive the
        # SIGKILL (no clean close -> no compaction, torn tail possible)
        http_json(
            "POST", f"{uris[0]}/index/e2e/query",
            {"query": 'SetRowAttrs(f, 0, label="alpha", rank=7)'},
            timeout=120,
        )
        # SIGKILL a replica mid-stream (no clean shutdown: open WALs)
        procs[2].send_signal(signal.SIGKILL)
        procs[2].wait(timeout=30)
        # the rest of the import goes to the survivors (write fan-out to a
        # dead replica is best-effort; AE repairs it after restart)
        http_json(
            "POST", f"{uris[0]}/index/e2e/field/f/import",
            {"rows": [0] * (len(all_cols) - half), "cols": all_cols[half:]},
            timeout=120,
        )
        (survivor_count,) = (
            http_json(
                "POST", f"{uris[0]}/index/e2e/query",
                {"query": "Count(Row(f=0))"}, timeout=120,
            )["results"]
        )
        assert survivor_count == len(all_cols)
        # restart the killed node: its fragments reopen via snapshot + WAL
        # replay (torn tail tolerated), then AE pulls what it missed
        procs[2] = _spawn_node(base, names[2], ports[2], hosts)
        _wait_up(uris[2])
        # every node runs an AE pass: each primary pushes repairs to its
        # replicas (the ticker would do this on anti-entropy.interval)
        for u in uris:
            http_json("POST", f"{u}/internal/sync", timeout=300)
        for u in uris:
            r = http_json(
                "POST", f"{u}/index/e2e/query",
                {"query": "Count(Row(f=0))"}, timeout=120,
            )
            assert r["results"][0] == len(all_cols), u
        # attrs survived the SIGKILL + restart (append-log replay) and
        # AE propagated them with the row data — assert on EVERY node,
        # including the restarted one (its store was repaired by attr AE)
        for u in uris:
            r = http_json(
                "POST", f"{u}/index/e2e/query",
                {"query": "Row(f=0)"}, timeout=120,
            )
            assert r["results"][0].get("attrs") == {
                "label": "alpha", "rank": 7,
            }, u
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_join_on_boot_subprocess():
    """A fresh `pilosa-tpu server --join <coordinator>` process
    self-registers, triggers the resize job and serves its shard subset —
    zero manual topology calls (reference: gossip join -> listenForJoins,
    cluster.go:1141,1796; VERDICT r2 #6 done-criterion)."""
    base = tempfile.mkdtemp(prefix="pilosa-join-")
    p0_port, p1_port = _free_port(), _free_port()
    uri0 = f"http://localhost:{p0_port}"
    uri1 = f"http://localhost:{p1_port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def spawn(name, port, extra):
        args = [
            sys.executable, "-m", "pilosa_tpu.cli", "server",
            "--data-dir", os.path.join(base, name),
            "--bind", f"localhost:{port}",
            "--node-id", name,
        ] + extra
        return subprocess.Popen(
            args, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        )

    procs = [spawn("c0", p0_port, [])]
    try:
        _wait_up(uri0)
        http_json("POST", f"{uri0}/index/jb", {"options": {}})
        http_json(
            "POST", f"{uri0}/index/jb/field/f", {"options": {"type": "set"}}
        )
        cols = [s * SHARD_WIDTH + 2 for s in range(16)]
        http_json(
            "POST", f"{uri0}/index/jb/field/f/import",
            {"rows": [0] * len(cols), "cols": cols}, timeout=120,
        )
        procs.append(spawn("j1", p1_port, ["--join", uri0]))
        _wait_up(uri1)
        # both processes converge to the 2-node NORMAL membership
        deadline = time.time() + 120
        while time.time() < deadline:
            s0 = http_json("GET", f"{uri0}/status", timeout=5)
            s1 = http_json("GET", f"{uri1}/status", timeout=5)
            if (
                len(s0["nodes"]) == 2
                and len(s1["nodes"]) == 2
                and s0["state"] == "NORMAL"
                and s1["state"] == "NORMAL"
            ):
                break
            time.sleep(0.3)
        else:
            raise AssertionError((s0, s1))
        # the joiner serves queries over the full index (owning some shards)
        r = http_json(
            "POST", f"{uri1}/index/jb/query",
            {"query": "Count(Row(f=0))"}, timeout=120,
        )
        assert r["results"][0] == len(cols)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
