"""Runtime lockset race detector (pilosa_tpu/utils/race.py) units +
the two historical-incident regressions.

The unit half drives the Eraser state machine deterministically with
events (never sleeps, no timing dependence): virgin -> exclusive ->
shared -> shared-modified transitions, the candidate-lockset
intersection, both-stack reports, annotation escapes, and the
zero-overhead passthrough contract.

The regression half reproduces, seeded-violation style, the two
concurrency incidents this gate exists to re-prevent:

* **PR 10** — the unserialized tally dispatch: the TopN tally called the
  compiled cross-counts program directly from fan-out leg threads; with
  mesh-sharded operands the program carries collectives and concurrent
  entry parked XLA-CPU's rendezvous. The fix routed every non-plan
  compiled dispatch through `plan.run_serialized`. Here the PRE-fix call
  shape is seeded into an exec/-scoped module and the static LOCK006
  rule must flag it; the POST-fix shape must pass.
* **PR 11** — the close-vs-commit-round ack race: `WalWriter.close()`
  sets `_closed` under the LRU lock while an in-flight commit round
  reads it under the commit lock — no common lock, so a round could
  observe a stale value, skip the writer, and ack bytes never fsynced.
  The fix made close() fsync UNCONDITIONALLY, which keeps the lock-free
  flag read but makes it harmless (the real `WalWriter` carries a
  race-check exclude citing exactly that). Here the PRE-fix decision
  structure is modeled and the runtime detector must record the race;
  the common-lock (race-free) structure must stay silent.
"""

import ast
import textwrap
import threading

import pytest

from pilosa_tpu import analysis
from pilosa_tpu.analysis.framework import Module
from pilosa_tpu.utils import locks, race


def _seeded(rel: str, src: str) -> Module:
    src = textwrap.dedent(src)
    return Module(path="/tmp/" + rel, rel=rel, source=src, tree=ast.parse(src))


def _drain():
    return race.drain()


def _fresh_class():
    cls = type("Shared", (), {})
    return race.instrument_class(cls)


def _run(thread_fn, name="peer"):
    t = threading.Thread(target=thread_fn, name=name)
    t.start()
    t.join(5.0)
    assert not t.is_alive()
    return t


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


class TestStateMachine:
    def test_single_thread_stays_exclusive_and_silent(self):
        cls = _fresh_class()
        o = cls()
        for i in range(5):
            o.x = i
            _ = o.x
        assert _drain() == []

    def test_read_only_sharing_never_reports(self):
        cls = _fresh_class()
        o = cls()
        o.x = 1  # exclusive in this thread

        def reader():
            for _ in range(3):
                _ = o.x  # shared, read-only: empty lockset is fine

        _run(reader)
        _ = o.x
        assert _drain() == []

    def test_write_write_no_common_lock_reports(self):
        cls = _fresh_class()
        o = cls()
        mu_b = locks.TrackedLock("race_test.mu_b")
        o.x = 1  # virgin -> exclusive(main)

        def writer_b():
            with mu_b:
                o.x = 2  # exclusive -> shared-modified, lockset={mu_b}

        _run(writer_b, name="writer-b")
        o.x = 3  # no lock: lockset empties in shared-modified -> report
        reports = _drain()
        assert len(reports) == 1
        r = reports[0]
        assert r.attr == "x"
        assert "shared-modified" in r.message

    def test_common_lock_keeps_lockset_nonempty(self):
        cls = _fresh_class()
        o = cls()
        mu = locks.TrackedLock("race_test.mu_common")
        with mu:
            o.x = 1

        def writer_b():
            with mu:
                o.x = 2

        _run(writer_b)
        with mu:
            o.x = 3
            _ = o.x
        assert _drain() == []

    def test_ownership_transfer_write_does_not_itself_report(self):
        # init in thread A, configure once in thread B (the NodeServer
        # boot shape): the handoff write alone must not fire
        cls = _fresh_class()
        o = cls()
        o.x = 1

        def configure():
            o.x = 2  # lock-free handoff: arms, does not report

        _run(configure)
        assert _drain() == []

    def test_read_after_armed_conflict_reports(self):
        cls = _fresh_class()
        o = cls()
        mu_b = locks.TrackedLock("race_test.mu_read")
        o.x = 1

        def writer_b():
            with mu_b:
                o.x = 2

        _run(writer_b, name="armed-writer")
        _ = o.x  # bare READ against a shared-modified attr -> report
        reports = _drain()
        assert len(reports) == 1
        assert "read with no consistently-held lock" in reports[0].message

    def test_one_report_per_attribute(self):
        cls = _fresh_class()
        o = cls()
        mu_b = locks.TrackedLock("race_test.mu_once")
        o.x = 1

        def writer_b():
            with mu_b:
                o.x = 2

        _run(writer_b)
        for i in range(4):
            o.x = 10 + i
        assert len(_drain()) == 1


# ---------------------------------------------------------------------------
# reports carry both stacks
# ---------------------------------------------------------------------------


class TestReports:
    def test_both_conflicting_stacks_recorded(self):
        cls = _fresh_class()
        o = cls()
        mu_b = locks.TrackedLock("race_test.mu_stacks")
        o.x = 1

        def the_armed_writer_site():
            with mu_b:
                o.x = 2

        def peer():
            the_armed_writer_site()

        _run(peer, name="stack-peer")

        def the_conflicting_site():
            o.x = 3

        the_conflicting_site()
        (r,) = _drain()
        assert "the_armed_writer_site" in r.stack_a
        assert "the_conflicting_site" in r.stack_b
        assert r.thread_a == "stack-peer"
        assert r.thread_b != r.thread_a

    def test_format_report_renders_both_sites(self):
        cls = _fresh_class()
        o = cls()
        o.x = 1

        def w():
            o.x = 2

        _run(w)
        o.x = 3
        try:
            txt = race.format_report()
            assert "candidate-race" in txt
            assert "prior access" in txt and "conflicting access" in txt
        finally:
            _drain()


# ---------------------------------------------------------------------------
# annotation escapes + passthrough
# ---------------------------------------------------------------------------


class TestEscapesAndOverhead:
    def test_exclude_exempts_attribute(self):
        cls = race.instrument_class(type("Excl", (), {}), exclude=("x",))
        o = cls()
        o.x = 1
        o.y = 1

        def w():
            o.x = 2
            o.y = 2

        _run(w)
        o.x = 3
        o.y = 3
        reports = _drain()
        assert [r.attr for r in reports] == ["y"]  # x escaped, y caught

    def test_lockish_attributes_never_tracked(self):
        cls = _fresh_class()
        o = cls()
        o._mu = locks.TrackedLock("race_test.self_mu")

        def w():
            _ = o._mu  # reading the lock attribute is not a data access

        _run(w)
        o._mu = locks.TrackedLock("race_test.self_mu2")
        assert _drain() == []

    @pytest.mark.skipif(
        race.enabled(), reason="passthrough contract only observable off"
    )
    def test_decorator_is_passthrough_when_disabled(self):
        class C:
            pass

        assert race.race_checked(C) is C
        assert "__getattribute__" not in C.__dict__
        assert "__setattr__" not in C.__dict__

        class D:
            pass

        assert race.race_checked(exclude=("x",))(D) is D
        assert "__getattribute__" not in D.__dict__

    def test_drain_clears_the_log(self):
        cls = _fresh_class()
        o = cls()
        o.x = 1

        def w():
            o.x = 2

        _run(w)
        o.x = 3
        assert len(race.drain()) == 1
        assert race.reports() == []
        assert race.format_report() == "race check: clean"


# ---------------------------------------------------------------------------
# historical regression: PR 11 close-vs-commit-round ack race
# ---------------------------------------------------------------------------


class _ModelWalWriter:
    """Structural model of the PR-11 incident: `_closed` written by
    close() under the LRU lock, read by the commit round under the
    commit lock. Pre-fix, the round's stale read decided whether acked
    bytes were ever fsynced."""

    def __init__(self):
        self.closed_flag = False
        self.acked_unsynced = False


def _drive_close_vs_commit(writer_cls, close_lock, commit_lock):
    """Deterministic interleaving: round reads -> close writes -> round
    re-reads (the commit loop re-checks every round)."""
    w = writer_cls()
    round_saw = threading.Event()
    closed = threading.Event()
    done = threading.Event()

    def commit_round():
        with commit_lock:
            _ = w.closed_flag  # round 1: writer looks open
        round_saw.set()
        closed.wait(5.0)
        with commit_lock:
            if not w.closed_flag:  # round 2: the racy skip decision
                w.acked_unsynced = True
        done.set()

    t = threading.Thread(target=commit_round, name="commit-round")
    t.start()
    assert round_saw.wait(5.0)
    with close_lock:
        w.closed_flag = True  # close(): the conflicting write
    closed.set()
    assert done.wait(5.0)
    t.join(5.0)


class TestPR11CloseVsCommitAckRace:
    def test_reverted_fix_is_caught_by_the_detector(self):
        """The pre-fix structure — close under lru_mu, round under
        commit_mu, NO common lock — must record a candidate race on the
        flag that gates the ack."""
        cls = race.instrument_class(
            type("ModelWalWriterReverted", (_ModelWalWriter,), {}),
        )
        _drive_close_vs_commit(
            cls,
            close_lock=locks.TrackedLock("race_test.wal.lru_mu"),
            commit_lock=locks.TrackedLock("race_test.wal.commit_mu"),
        )
        reports = _drain()
        assert any(r.attr == "closed_flag" for r in reports), reports

    def test_fixed_structure_is_silent(self):
        """With the decision taken under ONE mutex (the semantic effect
        of the real fix: close() fsyncs unconditionally, so the ack no
        longer depends on a cross-lock read), the detector stays quiet."""
        one_mu = locks.TrackedLock("race_test.wal.one_mu")
        cls = race.instrument_class(
            type("ModelWalWriterFixed", (_ModelWalWriter,), {}),
        )
        _drive_close_vs_commit(cls, close_lock=one_mu, commit_lock=one_mu)
        assert _drain() == []

    def test_real_walwriter_documents_the_benign_race(self):
        """The real WalWriter must carry the `_closed` race exclude —
        deleting it without re-proving the close() fix would let the
        CI race job miss a regression of this exact incident."""
        import inspect

        from pilosa_tpu.core import wal

        src = inspect.getsource(wal)
        deco = src.split("class WalWriter", 1)[0].rsplit("@race_checked", 1)[1]
        assert '"_closed"' in deco


# ---------------------------------------------------------------------------
# historical regression: PR 10 unserialized tally dispatch (static LOCK006)
# ---------------------------------------------------------------------------


_PRE_FIX_TALLY = """
    import jax

    @jax.jit
    def _counts_cross(src, planes):
        return src

    def tally(parts, src, planes, n, n_present):
        # PR-10 incident shape: compiled tally dispatched directly from a
        # fan-out leg thread, no run_serialized, no dispatch mutex
        parts.append(_counts_cross(src[None], planes)[0][:n, :n_present])
"""

_POST_FIX_TALLY = """
    import jax
    from pilosa_tpu.exec import plan as planmod

    @jax.jit
    def _counts_cross(src, planes):
        return src

    def tally(parts, src, planes, n, n_present):
        parts.append(
            planmod.run_serialized(
                lambda: _counts_cross(src[None], planes)[0][:n, :n_present]
            )
        )
"""


class TestPR10UnserializedTallyDispatch:
    def _lock006(self, src: str):
        m = _seeded("pilosa_tpu/exec/_seeded_tally.py", src)
        fs = analysis.run_passes([analysis.LockHygienePass()], [m])
        return [f for f in fs if f.code == "LOCK006"]

    def test_reverted_fix_is_caught_by_lock006(self):
        found = self._lock006(_PRE_FIX_TALLY)
        assert found, "the PR-10 incident shape must fail the gate"
        assert "_counts_cross" in found[0].message
        assert "PR-10" in found[0].message

    def test_fix_restored_passes(self):
        assert self._lock006(_POST_FIX_TALLY) == []

    def test_cross_module_revert_is_caught_too(self):
        """The same revert expressed against the REAL groupby module:
        a seeded exec/ caller invoking groupby's jitted cross-counts
        directly is flagged via cross-module jit discovery."""
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        gb = analysis.load_source_module(
            os.path.join(repo, "pilosa_tpu", "exec", "groupby.py"),
            rel="pilosa_tpu/exec/groupby.py",
        )
        caller = _seeded(
            "pilosa_tpu/exec/_seeded_caller.py",
            """
            from pilosa_tpu.exec import groupby as gb

            def tally(src, planes):
                return gb._counts_cross(src[None], planes)
            """,
        )
        fs = analysis.run_passes([analysis.LockHygienePass()], [gb, caller])
        assert any(
            f.code == "LOCK006"
            and f.path == "pilosa_tpu/exec/_seeded_caller.py"
            for f in fs
        )
