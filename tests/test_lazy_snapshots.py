"""Lazy host snapshot tier (VERDICT r2 SURVEY-partial #6): fragments open
by indexing snapshot headers + memmap; rows materialize on first access —
the host analog of the reference's zero-copy mmap storage
(fragment.go:311 openStorage, roaring.go:1437 RemapRoaringStorage)."""

import numpy as np
import pytest

from pilosa_tpu.core.fragment import Fragment, _LazyRows
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture
def snap_dir(tmp_path, rng):
    """A closed fragment on disk with 40 rows (mixed sparse/dense)."""
    frag = Fragment(str(tmp_path / "frags" / "0"), "i", "f", "standard", 0).open()
    expect = {}
    for row in range(40):
        n = 30_000 if row % 7 == 0 else 50 + row  # every 7th densifies
        cols = np.unique(rng.integers(0, SHARD_WIDTH, n).astype(np.uint64))
        frag.bulk_import(np.full(len(cols), row, np.uint64), cols)
        expect[row] = set(int(c) for c in cols)
    frag.snapshot()
    frag.close()
    return str(tmp_path / "frags" / "0"), expect


def test_open_is_lazy_and_reads_correct(snap_dir):
    path, expect = snap_dir
    frag = Fragment(path, "i", "f", "standard", 0).open()
    assert isinstance(frag._rows, _LazyRows)
    assert len(frag._rows._mat) == 0, "open materialized rows"
    # metadata answers without materializing
    assert frag.row_ids() == sorted(expect)
    assert frag.row_count(3) == len(expect[3])
    assert frag.row_count(7) == len(expect[7])
    assert len(frag._rows._mat) == 0, "count_of materialized rows"
    # cache rebuilt from header metadata (sidecar was flushed on close, so
    # it loads; drop it to force the lazy rebuild)
    frag.cache.clear()
    frag.recalculate_cache()
    assert frag.cache.get(7) == len(expect[7])
    assert len(frag._rows._mat) == 0, "cache rebuild materialized rows"
    # actual reads materialize only what they touch
    pos = frag.row_positions(5)
    assert set(int(p) for p in pos) == expect[5]
    assert set(frag._rows._mat) == {5}
    frag.close()


def test_mutations_on_lazy_rows(snap_dir):
    path, expect = snap_dir
    frag = Fragment(path, "i", "f", "standard", 0).open()
    assert frag.set_bit(9, 12345) == (12345 not in expect[9])
    expect[9].add(12345)
    assert frag.row_count(9) == len(expect[9])
    frag.clear_bit(9, 12345)
    expect[9].discard(12345)
    assert frag.row_count(9) == len(expect[9])
    # untouched rows still lazy
    assert 11 not in frag._rows._mat
    assert frag.row_count(11) == len(expect[11])
    frag.close()


def test_wal_replay_over_lazy_map(snap_dir):
    path, expect = snap_dir
    frag = Fragment(path, "i", "f", "standard", 0).open()
    frag.set_bit(4, 999_999)
    frag.close()  # WAL holds the op (no snapshot triggered)
    frag2 = Fragment(path, "i", "f", "standard", 0).open()
    assert frag2.contains(4, 999_999)
    assert frag2.row_count(4) == len(expect[4] | {999_999})
    # only the WAL-touched row materialized during replay
    assert 17 not in frag2._rows._mat
    frag2.close()


def test_snapshot_streams_unmaterialized_rows(snap_dir):
    """snapshot()/to_bytes() must serialize lazy rows from the memmap
    without materializing them, and rebase afterwards."""
    path, expect = snap_dir
    frag = Fragment(path, "i", "f", "standard", 0).open()
    frag.set_bit(0, 77)  # one materialized row
    expect[0].add(77)
    blob = frag.to_bytes()
    assert set(frag._rows._mat) == {0}, "to_bytes materialized rows"
    frag.snapshot()
    assert set(frag._rows._mat) == {0}, "snapshot materialized rows"
    # everything still correct after rebase
    for row in (0, 7, 13):
        got = set(int(p) for p in frag.row_positions(row))
        assert got == expect[row], row
    frag.close()
    # the streamed blob round-trips into another fragment
    frag3 = Fragment(None, "i", "f", "standard", 0)
    frag3.open()
    frag3.from_bytes(blob)
    for row in (0, 7, 39):
        assert set(int(p) for p in frag3.row_positions(row)) == expect[row]


def test_eager_mode_still_works(snap_dir, monkeypatch):
    from pilosa_tpu.core import fragment as fragmod

    path, expect = snap_dir
    monkeypatch.setattr(fragmod, "_LAZY_SNAPSHOTS", False)
    frag = Fragment(path, "i", "f", "standard", 0).open()
    assert not isinstance(frag._rows, _LazyRows)
    assert frag.row_count(3) == len(expect[3])
    frag.close()


def test_lazy_vs_eager_differential(snap_dir, monkeypatch, rng):
    """Same fragment, both modes: identical ids, counts, positions and
    block checksums."""
    from pilosa_tpu.core import fragment as fragmod

    path, _ = snap_dir
    lazy = Fragment(path, "i", "f", "standard", 0).open()
    with monkeypatch.context() as m:
        m.setattr(fragmod, "_LAZY_SNAPSHOTS", False)
        eager = Fragment(path, "i", "f", "standard", 0).open()
    assert lazy.row_ids() == eager.row_ids()
    for row in lazy.row_ids():
        assert lazy.row_count(row) == eager.row_count(row), row
    assert lazy.block_checksums() == eager.block_checksums()
    lazy.close()
    eager.close()


class TestWalFdCap:
    def test_open_wal_handles_bounded(self, tmp_path, monkeypatch):
        """Thousands of fragments must not hold thousands of WAL fds
        (reference: syswrap max-file-count). Evicted handles reopen
        transparently and data survives reopen."""
        from pilosa_tpu.core import wal as walmod

        monkeypatch.setattr(walmod, "_MAX_OPEN_WALS", 4)
        frags = []
        for i in range(12):
            f = Fragment(
                str(tmp_path / "v" / str(i)), "i", "f", "standard", i
            ).open()
            f.set_bit(1, 100 + i)
            frags.append(f)
        open_fds = sum(
            1 for w in walmod.WalWriter._lru.values() if w._f is not None
        )
        assert open_fds <= 4, open_fds
        # interleaved writes across all writers still land correctly
        for i, f in enumerate(frags):
            f.set_bit(2, 200 + i)
        for f in frags:
            f.close()
        for i in range(12):
            f = Fragment(
                str(tmp_path / "v" / str(i)), "i", "f", "standard", i
            ).open()
            assert f.contains(1, 100 + i) and f.contains(2, 200 + i), i
            f.close()

    def test_concurrent_appends_under_tiny_cap(self, tmp_path, monkeypatch):
        import threading

        from pilosa_tpu.core import wal as walmod

        monkeypatch.setattr(walmod, "_MAX_OPEN_WALS", 8)
        frags = [
            Fragment(str(tmp_path / "c" / str(i)), "i", "f", "standard", i).open()
            for i in range(16)
        ]
        errors = []

        def hammer(start):
            try:
                for k in range(60):
                    frags[(start + k) % 16].set_bit(k % 5, start * 1000 + k)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=hammer, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        for f in frags:
            f.close()
        # every write is durable across reopen
        reopened = [
            Fragment(str(tmp_path / "c" / str(i)), "i", "f", "standard", i).open()
            for i in range(16)
        ]
        for start in range(6):
            for k in range(60):
                assert reopened[(start + k) % 16].contains(k % 5, start * 1000 + k)
        for f in reopened:
            f.close()


def test_lazy_fragments_hold_no_fds(tmp_path, rng):
    """Lazy fragments must not retain per-fragment fds (open-per-access);
    a holder with thousands of fragments stays under RLIMIT_NOFILE."""
    import os as _os

    def nfds():
        return len(_os.listdir("/proc/self/fd"))

    frags = []
    for i in range(20):
        f = Fragment(str(tmp_path / "fd" / str(i)), "i", "f", "standard", i).open()
        f.bulk_import(np.zeros(5, np.uint64), np.arange(5, dtype=np.uint64) + i)
        f.snapshot()
        f.close()
        frags.append(f)
    base = nfds()
    reopened = [
        Fragment(str(tmp_path / "fd" / str(i)), "i", "f", "standard", i).open()
        for i in range(20)
    ]
    assert all(isinstance(f._rows, _LazyRows) for f in reopened)
    # each open fragment holds at most its WAL fd (LRU-capped), never a
    # snapshot fd; reading rows must not accumulate fds either
    for f in reopened:
        f.row_positions(0)
    grew = nfds() - base
    assert grew <= 21, grew  # WAL fds only (cap default 256 > 20)
    for f in reopened:
        f.close()
    assert nfds() <= base + 1


def test_mutex_fragment_reopen_under_paranoia(tmp_path, monkeypatch):
    """Regression (r3 review): reopening a mutex fragment with WAL ops
    under PILOSA_TPU_PARANOIA=1 must not false-positive — the mutex
    vector is rebuilt only after WAL replay."""
    from pilosa_tpu.core import rowstore

    monkeypatch.setattr(rowstore, "PARANOIA", True)
    path = str(tmp_path / "mx" / "0")
    frag = Fragment(path, "i", "m", "standard", 0, mutex=True).open()
    frag.set_bit(1, 10)
    frag.set_bit(1, 11)
    frag.snapshot()
    frag.set_bit(1, 12)  # lands in the WAL only
    frag.close()
    frag2 = Fragment(path, "i", "m", "standard", 0, mutex=True).open()
    assert frag2.contains(1, 10) and frag2.contains(1, 12)
    # mutex semantics intact after reopen: a new row steals the column
    frag2.set_bit(2, 10)
    assert not frag2.contains(1, 10) and frag2.contains(2, 10)
    frag2.close()
