"""Cross-fragment deferred-delta merge tests (ISSUE 9 tentpole).

The staged write path's read barrier no longer pays one host merge per
fragment: core/merge.py gathers every staged fragment's pending buffers
a read is about to touch and sort/dedups the whole burst in ONE batched
pass — a compiled device program above the `merge-device-threshold`
crossover, one vectorized host pass below it. These tests pin down:

- kernel-level equivalence: ops/merge.py's device sort/dedup/bit-cumsum
  vs the vectorized host path, bit-identical on duplicate-heavy bursts,
- the ONE-launch contract: a staged burst across >= 100 fragments pays
  exactly one device program launch (counter-asserted — the acceptance
  criterion),
- differential barrier equivalence vs naive per-bit semantics and vs
  the per-fragment host merge, across duplicates, interleaved set/clear
  batches and rank-cache TopN order (this file is in test_stress.py's
  shard-width matrix, so the same assertions re-run at exponents 16/22),
- the crossover-threshold boundary on both sides,
- the WAL replay fast path (satellite): staged OP_SET frames re-stage at
  open() and land via ONE deferred merge, bit-identical to the pre-crash
  state including rank-cache order,
- concurrent readers racing a barrier: the generation handshake keeps
  the merge exactly-once and never drops a delta.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.core import merge as merge_mod
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.ops import merge as ops_merge
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture(autouse=True)
def _merge_env():
    """Restore the process-global crossover knob and counters around
    every test (configure() is process-global like the [hbm] knobs;
    the RAW value is saved so None round-trips back to backend AUTO)."""
    old = merge_mod._device_threshold
    yield
    merge_mod.configure(device_threshold=old)
    merge_mod.reset_stats()
    ops_merge.reset_stats()


def _pairs_set(field):
    """{(row, absolute_col)} across every standard-view fragment — a
    host read, so it forces the per-fragment read barrier."""
    out = set()
    v = field.view("standard")
    if v is None:
        return out
    for s in v.available_shards():
        rows, cols = v.fragments[s].pairs()
        base = s * SHARD_WIDTH
        out.update(
            (int(r), int(c) + base)
            for r, c in zip(rows.tolist(), cols.tolist())
        )
    return out


def _cache_tops(field):
    """{shard: rank-cache top pairs} — TopN order must survive however
    the merge ran."""
    v = field.view("standard")
    return {s: v.fragments[s].cache_top() for s in v.available_shards()}


def _burst(rng, n, n_shards, row_lo=0, row_hi=12):
    rows = rng.integers(row_lo, row_hi, n).astype(np.uint64)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, n).astype(np.uint64)
    return rows, cols


class TestKernelEquivalence:
    """ops/merge.py device program vs vectorized host pass."""

    def test_sorted_unique_and_cumsum_identical(self):
        rng = np.random.default_rng(3)
        keys = rng.integers(0, 1 << 40, 5000).astype(np.uint64)
        keys = np.concatenate([keys, keys[:1700], keys[:11]])  # dup-heavy
        md, cd = ops_merge.merge_keys_device(keys)
        mh, ch = ops_merge.merge_keys_host(keys)
        np.testing.assert_array_equal(md, mh)
        np.testing.assert_array_equal(cd, ch)
        assert md.dtype == np.uint64 and len(md) == len(np.unique(keys))

    def test_word_or_matches_reference(self):
        rng = np.random.default_rng(4)
        pos = np.unique(rng.integers(0, SHARD_WIDTH, 4000).astype(np.uint64))
        merged, cum = ops_merge.merge_keys_host(pos)
        widx, wvals = ops_merge.word_or_from_sorted(merged, cum)
        want = np.zeros(SHARD_WIDTH // 32, np.uint32)
        for p in pos.tolist():
            want[p >> 5] |= np.uint32(1) << np.uint32(p & 31)
        got = np.zeros_like(want)
        got[widx] = wvals
        np.testing.assert_array_equal(got, want)

    def test_word_or_mid_slice(self):
        """word_or_from_sorted on a SLICE whose cumsum does not start at
        the first key (the per-fragment split case): the wrapped base
        subtraction must stay exact."""
        rng = np.random.default_rng(5)
        pos = np.unique(rng.integers(0, SHARD_WIDTH, 3000).astype(np.uint64))
        merged, cum = ops_merge.merge_keys_host(pos)
        lo = len(merged) // 3
        widx, wvals = ops_merge.word_or_from_sorted(merged[lo:], cum[lo:])
        want = np.zeros(SHARD_WIDTH // 32, np.uint32)
        for p in merged[lo:].tolist():
            want[p >> 5] |= np.uint32(1) << np.uint32(p & 31)
        got = np.zeros_like(want)
        got[widx] = wvals
        np.testing.assert_array_equal(got, want)

    def test_empty_word_or(self):
        widx, wvals = ops_merge.word_or_from_sorted(
            np.empty(0, np.uint64), np.empty(0, np.uint32)
        )
        assert len(widx) == 0 and len(wvals) == 0


class TestBarrierDifferential:
    """View-level barrier vs naive per-bit semantics and vs the
    per-fragment host merge — bit-identical, TopN order included."""

    def _drive(self, threshold, batches, clears=()):
        """One holder driven through the staged path with the given
        crossover threshold; clears (exact path) interleave after the
        listed batch index. Returns (pairs, cache_tops)."""
        merge_mod.configure(device_threshold=threshold)
        h = Holder().open()
        f = h.create_index("dx").create_field("f", FieldOptions())
        clears = dict(clears)
        for i, (rows, cols) in enumerate(batches):
            f.import_bits(rows, cols)
            if i in clears:
                crows, ccols = clears[i]
                f.import_bits(crows, ccols, clear=True)
            if i % 2 == 1:
                # barrier mid-stream: reads between batches must always
                # see the union of everything staged so far
                f.view("standard").sync_pending()
        f.view("standard").sync_pending()
        return _pairs_set(f), _cache_tops(f)

    def test_device_host_naive_identical_with_duplicates(self):
        rng = np.random.default_rng(11)
        n_shards = 6
        batches = []
        for _ in range(4):
            rows, cols = _burst(rng, 3000, n_shards)
            # duplicates inside AND across batches
            batches.append(
                (np.concatenate([rows, rows[:500]]),
                 np.concatenate([cols, cols[:500]]))
            )
        dev_pairs, dev_tops = self._drive(0, batches)  # always device
        host_pairs, host_tops = self._drive(-1, batches)  # never device
        assert dev_pairs == host_pairs
        assert dev_tops == host_tops
        # ground truth: naive per-bit exact writes
        h = Holder().open()
        f = h.create_index("nv").create_field("f", FieldOptions())
        want = set()
        for rows, cols in batches:
            for r, c in zip(rows.tolist(), cols.tolist()):
                f.set_bit(int(r), int(c))
                want.add((int(r), int(c)))
        assert dev_pairs == want == _pairs_set(f)
        assert dev_tops == _cache_tops(f)

    def test_interleaved_set_clear_batches(self):
        rng = np.random.default_rng(12)
        n_shards = 4
        b0 = _burst(rng, 2000, n_shards)
        b1 = _burst(rng, 2000, n_shards)
        b2 = _burst(rng, 2000, n_shards)
        # clear half of batch 0 right after batch 1 staged
        clears = {1: (b0[0][:1000], b0[1][:1000])}
        dev = self._drive(0, [b0, b1, b2], clears)
        host = self._drive(-1, [b0, b1, b2], clears)
        assert dev == host
        # naive ground truth, same order
        h = Holder().open()
        f = h.create_index("nv2").create_field("f", FieldOptions())
        for i, (rows, cols) in enumerate([b0, b1, b2]):
            for r, c in zip(rows.tolist(), cols.tolist()):
                f.set_bit(int(r), int(c))
            if i == 1:
                for r, c in zip(b0[0][:1000].tolist(), b0[1][:1000].tolist()):
                    f.clear_bit(int(r), int(c))
        assert dev[0] == _pairs_set(f)
        assert dev[1] == _cache_tops(f)

    def test_one_launch_for_120_fragments(self):
        """THE acceptance counter: a staged burst across >= 100 fragments
        pays ONE device program launch at the barrier, not one per
        fragment — and the merged bits are exact."""
        merge_mod.configure(device_threshold=0)
        n_shards = 120
        h = Holder().open()
        f = h.create_index("burstx").create_field("f", FieldOptions())
        rng = np.random.default_rng(13)
        n = 60_000
        rows = rng.integers(0, 8, n).astype(np.uint64)
        # at least one position in EVERY fragment
        cols = np.concatenate(
            [
                (np.arange(n_shards, dtype=np.uint64) * SHARD_WIDTH),
                rng.integers(0, n_shards * SHARD_WIDTH, n - n_shards).astype(
                    np.uint64
                ),
            ]
        )
        f.import_bits(rows, cols)
        v = f.view("standard")
        staged = [fr for fr in v.fragments.values() if fr._pending_n]
        assert len(staged) >= 100  # the burst really spans the matrix
        ops_merge.reset_stats()
        merge_mod.reset_stats()
        v.sync_pending()
        assert ops_merge.MERGE_STATS["device_launches"] == 1
        snap = merge_mod.stats_snapshot()
        assert snap["barriers"] == 1 and snap["device"] == 1
        assert snap["positions"] == n
        # every fragment drained in that one pass
        assert not any(fr._pending_n for fr in v.fragments.values())
        want = set(zip(rows.tolist(), cols.tolist()))
        assert _pairs_set(f) == want

    def test_crossover_boundary_both_sides(self):
        merge_mod.configure(device_threshold=1000)
        h = Holder().open()
        f = h.create_index("thr").create_field("f", FieldOptions())
        rng = np.random.default_rng(14)
        # burst of 999 raw positions: stays on the batched host path
        rows, cols = _burst(rng, 999, 3)
        f.import_bits(rows, cols)
        ops_merge.reset_stats()
        f.view("standard").sync_pending()
        assert ops_merge.MERGE_STATS["device_launches"] == 0
        assert ops_merge.MERGE_STATS["host_merges"] == 1
        # burst of exactly 1000: dispatches the device program
        rows, cols = _burst(rng, 1000, 3)
        f.import_bits(rows, cols)
        ops_merge.reset_stats()
        f.view("standard").sync_pending()
        assert ops_merge.MERGE_STATS["device_launches"] == 1
        assert ops_merge.MERGE_STATS["host_merges"] == 0

    def test_auto_crossover_resolves_by_backend(self):
        """Unset threshold = AUTO: device-off on the CPU backend (the
        XLA sort is the same silicon, ~6x slower than np.unique — the
        dispatch can never pay), 65536 on a real accelerator. A large
        burst under AUTO on CPU must therefore stay on the batched
        host path, still as ONE cross-fragment pass."""
        import jax

        merge_mod.configure(device_threshold=None)
        want = -1 if jax.default_backend() == "cpu" else 65536
        assert merge_mod.device_threshold() == want
        if want != -1:
            pytest.skip("accelerator backend: device path is the point")
        h = Holder().open()
        f = h.create_index("autox").create_field("f", FieldOptions())
        rng = np.random.default_rng(16)
        # big enough to clear any accelerator threshold's intent, small
        # enough per fragment not to trip the op-count snapshot (which
        # merges eagerly)
        f.import_bits(*_burst(rng, 30_000, 6))
        ops_merge.reset_stats()
        merge_mod.reset_stats()
        f.view("standard").sync_pending()
        assert ops_merge.MERGE_STATS["device_launches"] == 0
        assert ops_merge.MERGE_STATS["host_merges"] == 1
        snap = merge_mod.stats_snapshot()
        assert snap["barriers"] == 1 and snap["device"] == 0

    def test_concurrent_reader_races_barrier_exactly_once(self):
        """Readers hitting the per-fragment `_sync_locked` barrier while
        the view barrier merges the same burst: the generation handshake
        must keep every bit exactly once and never lose a delta."""
        merge_mod.configure(device_threshold=0)
        h = Holder().open()
        f = h.create_index("race").create_field("f", FieldOptions())
        rng = np.random.default_rng(15)
        want = set()
        errs = []
        for round_i in range(6):
            rows, cols = _burst(rng, 4000, 5)
            f.import_bits(rows, cols)
            want |= set(zip(rows.tolist(), cols.tolist()))
            v = f.view("standard")

            def reader():
                try:
                    for fr in list(v.fragments.values()):
                        fr.row_count(0)  # per-fragment read barrier
                except Exception as e:  # noqa: BLE001 - collected
                    errs.append(e)

            t = threading.Thread(target=reader)
            t.start()
            v.sync_pending()
            t.join()
        assert not errs, errs[:1]
        assert _pairs_set(f) == want


class TestAdmissionSurcharge:
    def test_staged_delta_bytes_visible_to_cost_estimate(self):
        """A query arriving mid-burst pays the merge before its first
        dispatch, so admission must see the staged delta's bytes
        (8-byte position keys) on top of the operand estimate — and the
        barrier's parked layers keep billing until a host read
        materializes them (a cold stack build would pay that merge)."""
        from pilosa_tpu.pql import parse
        from pilosa_tpu.sched import cost as costmod

        def materialize(field):
            for fr in field.view("standard").fragments.values():
                fr.sync_pending_now()

        h = Holder().open()
        f = h.create_index("adm").create_field("f", FieldOptions())
        rng = np.random.default_rng(31)
        f.import_bits(*_burst(rng, 100, 2))
        materialize(f)  # start from a fully materialized state
        idx = h.index("adm")
        q = parse("Count(Row(f=0))")
        c0 = costmod.estimate(idx, q, [0, 1])
        n = 5000
        f.import_bits(*_burst(rng, n, 2))
        c1 = costmod.estimate(idx, q, [0, 1])
        assert c1.device_bytes == c0.device_bytes + n * 8
        # the barrier dedups the burst but PARKS the merged layers: the
        # bill shrinks to the merged key count, not to zero
        f.view("standard").sync_pending()
        c2 = costmod.estimate(idx, q, [0, 1])
        parked = sum(
            fr._premerged_n
            for fr in f.view("standard").fragments.values()
        )
        assert 0 < parked <= n
        assert c2.device_bytes == c0.device_bytes + parked * 8
        # ...and it disappears once host reads materialize the layers
        materialize(f)
        c3 = costmod.estimate(idx, q, [0, 1])
        assert c3.device_bytes == c0.device_bytes


class TestWalReplayFastPath:
    """Satellite: opening a fragment with many staged OP_SET frames lands
    them via one deferred merge, not one exact apply per frame."""

    def _stage_and_crash(self, tmp_path, n_frames=8):
        frag = Fragment(str(tmp_path / "w"), "i", "f", "standard", 0).open()
        rng = np.random.default_rng(21)
        for _ in range(n_frames):
            # fragment positions: row * SHARD_WIDTH + col
            pos = rng.integers(0, 8, 500).astype(np.uint64) * np.uint64(
                SHARD_WIDTH
            ) + rng.integers(0, SHARD_WIDTH, 500).astype(np.uint64)
            frag.stage_positions(pos)
        pairs = frag.pairs()  # read barrier: merges, WAL keeps the frames
        top = frag.cache_top()
        frag._wal.close()  # crash: no snapshot, no cache flush
        frag._wal = None
        return (
            {(int(r), int(c)) for r, c in zip(*map(np.ndarray.tolist, pairs))},
            top,
        )

    def test_replay_equivalence_and_one_merge(self, tmp_path, monkeypatch):
        want_pairs, want_top = self._stage_and_crash(tmp_path, n_frames=8)
        calls = []
        real = merge_mod.note_host_sync
        monkeypatch.setattr(
            merge_mod,
            "note_host_sync",
            lambda n: (calls.append(n), real(n))[1],
        )
        frag2 = Fragment(str(tmp_path / "w"), "i", "f", "standard", 0).open()
        # ONE deferred merge covering every staged frame — not 8 applies
        assert calls == [8]
        rows, cols = frag2.pairs()
        got = {(int(r), int(c)) for r, c in zip(rows.tolist(), cols.tolist())}
        assert got == want_pairs
        assert frag2.cache_top() == want_top
        assert frag2._pending_n == 0  # open() returns a merged fragment
