"""Checked-in roaring-decoder crasher corpus (VERDICT r2 #7b).

The reference keeps confirmed unmarshal crashers in its repo
(roaring/fuzz_test.go:21-76). Here every `bad_*.bin` must raise
RoaringError in BOTH decoders (numpy and C++) — never crash, hang or
return data — and every `ok_*.bin` must decode identically in both.
Regenerate with tests/corpus/make_roaring_corpus.py.
"""

import glob
import os

import numpy as np
import pytest

from pilosa_tpu import native
from pilosa_tpu.core import roaring_io

CORPUS = os.path.join(os.path.dirname(__file__), "corpus", "roaring")
FILES = sorted(glob.glob(os.path.join(CORPUS, "*.bin")))


def _load(path):
    with open(path, "rb") as f:
        return f.read()


def test_corpus_present():
    names = {os.path.basename(p) for p in FILES}
    assert len([n for n in names if n.startswith("bad_")]) >= 15
    assert len([n for n in names if n.startswith("ok_")]) >= 4


@pytest.mark.parametrize("path", FILES, ids=[os.path.basename(p) for p in FILES])
def test_python_decoder(path):
    data = _load(path)
    if os.path.basename(path).startswith("bad_"):
        with pytest.raises(roaring_io.RoaringError):
            roaring_io.decode(data)
    else:
        out = roaring_io.decode(data)
        assert np.all(np.diff(out.astype(np.int64)) > 0) or len(out) <= 1


@pytest.mark.parametrize("path", FILES, ids=[os.path.basename(p) for p in FILES])
def test_native_decoder(path):
    if not native.available():
        pytest.skip("native codec unavailable")
    data = _load(path)
    if os.path.basename(path).startswith("bad_"):
        with pytest.raises(roaring_io.RoaringError):
            native.roaring_decode(data)
    else:
        got = native.roaring_decode(data)
        want = roaring_io.decode(data)
        assert np.array_equal(got, want), os.path.basename(path)


def test_corpus_ok_roundtrip():
    """ok_ files with pilosa dialect also survive re-encode round trips."""
    for path in FILES:
        name = os.path.basename(path)
        if not name.startswith("ok_") or "official" in name:
            continue
        pos = roaring_io.decode(_load(path))
        again = roaring_io.decode(roaring_io.encode(pos))
        assert np.array_equal(pos, again), name
