"""Attr store: incremental append-log persistence + compaction
(VERDICT r4 #5 — set_attrs must stop rewriting the whole store per write;
reference: boltdb/attrstore.go:82-332 page writes).
"""

import json
import os

import pytest

from pilosa_tpu.core import attrs as attrsmod
from pilosa_tpu.core.attrs import AttrStore


@pytest.fixture()
def path(tmp_path):
    return str(tmp_path / "attrs" / "store.json")


class TestIncremental:
    def test_set_appends_instead_of_rewriting_base(self, path):
        st = AttrStore(path)
        st.set_attrs(1, {"a": 1})
        base_exists = os.path.exists(path)
        log_size1 = os.path.getsize(st._log_path)
        for i in range(50):
            st.set_attrs(i, {"x": i})
        # base snapshot untouched by incremental writes; log grew
        assert os.path.exists(path) == base_exists
        assert not os.path.exists(path)  # never written until compaction
        assert os.path.getsize(st._log_path) > log_size1

    def test_reopen_replays_log(self, path):
        st = AttrStore(path)
        st.set_attrs(7, {"name": "x", "n": 3})
        st.set_attrs(7, {"n": 4, "gone": "y"})
        st.set_attrs(7, {"gone": None})
        st.set_attrs(205, {"z": True})
        st2 = AttrStore(path)
        assert st2.attrs(7) == {"name": "x", "n": 4}
        assert st2.attrs(205) == {"z": True}
        assert st2.ids() == [7, 205]
        assert st2.blocks() == st.blocks()

    def test_bulk_none_is_not_delete_across_reopen(self, path):
        st = AttrStore(path)
        st.set_attrs(3, {"keep": 1})
        st.set_bulk_attrs({3: {"keep": None, "new": 2}})
        assert st.attrs(3) == {"keep": 1, "new": 2}
        st2 = AttrStore(path)
        assert st2.attrs(3) == {"keep": 1, "new": 2}

    def test_torn_tail_ignored(self, path):
        st = AttrStore(path)
        st.set_attrs(1, {"a": 1})
        st.set_attrs(2, {"b": 2})
        with open(st._log_path, "a") as f:
            f.write('{"3": {"c"')  # crash mid-append: no newline
        st2 = AttrStore(path)
        assert st2.attrs(1) == {"a": 1}
        assert st2.attrs(2) == {"b": 2}
        assert 3 not in st2.ids()

    def test_write_after_torn_tail_survives_next_restart(self, path):
        """The torn tail must be TRUNCATED on replay: otherwise the next
        append concatenates onto the torn line and an ACKNOWLEDGED write
        silently vanishes on the restart after that (code-review r5
        confirmed repro)."""
        st = AttrStore(path)
        st.set_attrs(1, {"a": 1})
        with open(st._log_path, "a") as f:
            f.write('{"3": {"c"')  # torn append
        st2 = AttrStore(path)  # replay truncates the torn tail
        st2.set_attrs(9, {"ok": True})  # acknowledged write
        st3 = AttrStore(path)
        assert st3.attrs(9) == {"ok": True}
        assert st3.attrs(1) == {"a": 1}

    def test_close_releases_log_fd_and_reopens_on_write(self, path):
        st = AttrStore(path)
        st.set_attrs(1, {"a": 1})
        assert st._log_f is not None
        st.close()
        assert st._log_f is None
        st.set_attrs(2, {"b": 2})  # reopens transparently
        st2 = AttrStore(path)
        assert st2.attrs(2) == {"b": 2}

    def test_compaction_folds_log_into_base(self, path, monkeypatch):
        monkeypatch.setattr(attrsmod, "COMPACT_THRESHOLD", 10)
        st = AttrStore(path)
        for i in range(25):
            st.set_attrs(i % 4, {"v": i})
        # compacted at least twice: base exists, log is short again
        assert os.path.exists(path)
        with open(st._log_path) as f:
            assert len(f.readlines()) < 10
        with open(path) as f:
            base = json.load(f)
        # base holds state as of the LAST compaction (i=19); later writes
        # live only in the log until the next fold
        assert base["0"]["v"] == 16
        st2 = AttrStore(path)
        assert st2.attrs(0) == {"v": 24}
        assert st2.attrs(3) == {"v": 23}

    def test_compaction_on_reopen(self, path, monkeypatch):
        st = AttrStore(path)
        for i in range(30):
            st.set_attrs(i, {"v": i})
        monkeypatch.setattr(attrsmod, "COMPACT_THRESHOLD", 10)
        st2 = AttrStore(path)  # 30 logged lines >= 10: compacts on open
        with open(st2._log_path) as f:
            assert f.read() == ""
        assert os.path.exists(path)
        st3 = AttrStore(path)
        assert st3.attrs(29) == {"v": 29}

    def test_crash_between_base_replace_and_truncate(self, path):
        """Replaying an already-compacted delta over the new base must be
        idempotent (the documented crash window in _compact)."""
        st = AttrStore(path)
        st.set_attrs(5, {"a": 1, "d": "x"})
        st.set_attrs(5, {"d": None, "b": 2})
        log = open(st._log_path).read()
        st._compact()
        # simulate the crash: log restored as if truncate never happened
        with open(st._log_path, "w") as f:
            f.write(log)
        st2 = AttrStore(path)
        assert st2.attrs(5) == {"a": 1, "b": 2}

    def test_in_memory_store_has_no_files(self):
        st = AttrStore(None)
        st.set_attrs(1, {"a": 1})
        assert st.attrs(1) == {"a": 1}
