"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is the
in-process multi-node cluster harness, /root/reference/test/pilosa.go:390
MustRunCluster). Real-TPU behavior is exercised by bench.py and the driver's
compile checks, not by the unit suite.

force_cpu must run before anything initializes a JAX backend — the hosted
environment's sitecustomize pre-registers a tunneled TPU backend that would
otherwise be dialed (and can hang) even for CPU-only tests.
"""

from pilosa_tpu.utils.cpuonly import force_cpu

force_cpu(8)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process E2E tests (boot real server processes)"
    )
