"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is the
in-process multi-node cluster harness, /root/reference/test/pilosa.go:390
MustRunCluster). Real-TPU behavior is exercised by bench.py and the driver's
compile checks, not by the unit suite.

force_cpu must run before anything initializes a JAX backend — the hosted
environment's sitecustomize pre-registers a tunneled TPU backend that would
otherwise be dialed (and can hang) even for CPU-only tests.
"""

import os

# Lock-discipline checking must be on BEFORE any pilosa_tpu module is
# imported: module-level locks (plan._DISPATCH_MU, faults._global_mu, ...)
# are created at import time and only locks created while checking is
# enabled are tracked. Under this flag every lock in the package records
# acquisition ordering; any AB/BA cycle or self-deadlock fails the test
# that produced it (see _lock_discipline_guard below) with both stacks.
os.environ.setdefault("PILOSA_TPU_LOCK_CHECK", "1")

from pilosa_tpu.utils.cpuonly import force_cpu

force_cpu(8)

import numpy as np
import pytest

from pilosa_tpu.utils import locks
from pilosa_tpu.utils import race


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _lock_discipline_guard():
    """Fail any test whose execution recorded a lock-order cycle or a
    same-thread re-acquisition of a non-reentrant lock. The order graph
    accumulates across tests on purpose (an AB edge from one test plus a
    BA edge from another is still a real ordering conflict in the same
    process), but violations are attributed to the test that completed
    the bad pattern."""
    before = len(locks.violations())
    yield
    vs = locks.violations()[before:]
    if vs:
        report = "\n\n".join(v.render() for v in vs)
        pytest.fail(
            f"lock discipline violated ({len(vs)} finding(s)):\n{report}",
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _race_guard():
    """Fail any test whose execution recorded a candidate data race on a
    @race_checked class (Eraser lockset state machine, utils/race.py).
    Active only under PILOSA_TPU_RACE_CHECK=1 — the dedicated CI job
    runs the concurrency-heavy subset with it; plain tier-1 pays zero
    overhead. Tests that seed races on purpose drain() them before
    returning."""
    if not race.enabled():
        yield
        return
    before = len(race.reports())
    yield
    rs = race.reports()[before:]
    if rs:
        report = "\n\n".join(r.render() for r in rs)
        pytest.fail(
            f"candidate data race(s) recorded ({len(rs)}):\n{report}",
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _sched_leak_guard():
    """State-leak guard for admission control: every AdmissionController
    alive after a test must be idle — a shed or finished query that
    leaves a queue entry or a held concurrency slot behind would starve
    every later query on that node."""
    yield
    from pilosa_tpu.sched import admission

    leaked = admission.leaked_state()
    if leaked:
        pytest.fail(
            "admission controller(s) left non-idle (id, queued, inflight): "
            f"{leaked}"
        )


@pytest.fixture(autouse=True)
def _hbm_pin_leak_guard():
    """State-leak guard for HBM extent pins (pilosa_tpu/hbm/): every pin
    staging takes must be released by the plan's dispatch finally or an
    executor error path. A leaked pin makes its bytes permanently
    unevictable — the budget wedges a little tighter on every leak."""
    yield
    from pilosa_tpu.core.devcache import DEVICE_CACHE

    snap = DEVICE_CACHE.stats_snapshot()
    if snap["pinned_bytes"]:
        # clean up so one leak doesn't cascade into later tests
        DEVICE_CACHE.clear()
        pytest.fail(
            f"device-cache extent pins leaked: {snap['pinned_bytes']} "
            "bytes still pinned after the test"
        )


@pytest.fixture(autouse=True)
def _result_cache_isolation():
    """Result-cache isolation (core/resultcache.py): the store is
    process-global like DEVICE_CACHE, so entries, counters and the
    configured budget must not leak across tests — reset to defaults
    afterwards (the cache stays ENABLED suite-wide: every repeat query
    in the suite then exercises revalidation against the recompute the
    test asserts, which is free differential coverage)."""
    yield
    from pilosa_tpu.core import resultcache

    resultcache.RESULT_CACHE.reset()
    resultcache.RESULT_CACHE.configure(
        budget_bytes=resultcache.DEFAULT_BUDGET_BYTES, repair=True
    )


@pytest.fixture(autouse=True)
def _fault_plane_leak_guard():
    """State-leak guard: a test that installs a process-global
    FaultInjector or BreakerRegistry (faults.install_injector /
    install_breakers) and forgets to uninstall it would silently poison
    every later test's internode traffic — fail loudly instead."""
    yield
    from pilosa_tpu.server import faults

    leaked = []
    if faults.global_injector() is not None:
        faults.uninstall_injector()
        leaked.append("FaultInjector")
    if faults.global_breakers() is not None:
        faults.uninstall_breakers()
        leaked.append("BreakerRegistry")
    if leaked:
        pytest.fail(
            f"test left a global {' and '.join(leaked)} installed "
            "(faults.uninstall_injector()/uninstall_breakers() missing)"
        )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process E2E tests (boot real server processes)"
    )
