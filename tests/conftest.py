"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is the
in-process multi-node cluster harness, /root/reference/test/pilosa.go:390
MustRunCluster). Real-TPU behavior is exercised by bench.py and the driver's
compile checks, not by the unit suite.

Env must be set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)
