"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is the
in-process multi-node cluster harness, /root/reference/test/pilosa.go:390
MustRunCluster). Real-TPU behavior is exercised by bench.py and the driver's
compile checks, not by the unit suite.

force_cpu must run before anything initializes a JAX backend — the hosted
environment's sitecustomize pre-registers a tunneled TPU backend that would
otherwise be dialed (and can hang) even for CPU-only tests.
"""

import os

# Lock-discipline checking must be on BEFORE any pilosa_tpu module is
# imported: module-level locks (plan._DISPATCH_MU, faults._global_mu, ...)
# are created at import time and only locks created while checking is
# enabled are tracked. Under this flag every lock in the package records
# acquisition ordering; any AB/BA cycle or self-deadlock fails the test
# that produced it (see _lock_discipline_guard below) with both stacks.
os.environ.setdefault("PILOSA_TPU_LOCK_CHECK", "1")

from pilosa_tpu.utils.cpuonly import force_cpu

force_cpu(8)

import numpy as np
import pytest

from pilosa_tpu.utils import locks
from pilosa_tpu.utils import race


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _lock_discipline_guard():
    """Fail any test whose execution recorded a lock-order cycle or a
    same-thread re-acquisition of a non-reentrant lock. The order graph
    accumulates across tests on purpose (an AB edge from one test plus a
    BA edge from another is still a real ordering conflict in the same
    process), but violations are attributed to the test that completed
    the bad pattern."""
    before = len(locks.violations())
    yield
    vs = locks.violations()[before:]
    if vs:
        report = "\n\n".join(v.render() for v in vs)
        pytest.fail(
            f"lock discipline violated ({len(vs)} finding(s)):\n{report}",
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _race_guard():
    """Fail any test whose execution recorded a candidate data race on a
    @race_checked class (Eraser lockset state machine, utils/race.py).
    Active only under PILOSA_TPU_RACE_CHECK=1 — the dedicated CI job
    runs the concurrency-heavy subset with it; plain tier-1 pays zero
    overhead. Tests that seed races on purpose drain() them before
    returning."""
    if not race.enabled():
        yield
        return
    before = len(race.reports())
    yield
    rs = race.reports()[before:]
    if rs:
        report = "\n\n".join(r.render() for r in rs)
        pytest.fail(
            f"candidate data race(s) recorded ({len(rs)}):\n{report}",
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _resource_leak_guard():
    """Unified state-leak guard (utils/resources.py). Two layers:

    - always-on probes, one per runtime-guarded resource class, with the
      exact semantics of the three guards this fixture replaced: every
      live AdmissionController must be idle (a leaked queue entry or
      held slot starves every later query), device-cache pinned bytes
      must be zero (a leaked pin is permanently unevictable; the cache
      is cleared on failure so one leak doesn't cascade), and no
      process-global FaultInjector/BreakerRegistry may remain installed
      (uninstalled on failure for the same reason);
    - under PILOSA_TPU_RESOURCE_CHECK=1, per-class acquire/release
      balances — any nonzero balance fails the test with the leaked
      acquisition's stack. The dedicated CI job runs the concurrency
      subset with it; plain tier-1 pays zero overhead.
    """
    yield
    # importing here (not at conftest top) keeps collection light and
    # matches the replaced guards' lazy-import timing; each import
    # registers that subsystem's probe with the ledger
    from pilosa_tpu.core import devcache  # noqa: F401
    from pilosa_tpu.sched import admission  # noqa: F401
    from pilosa_tpu.server import faults  # noqa: F401
    from pilosa_tpu.utils import resources

    failures = resources.check_and_reset()
    if failures:
        pytest.fail(
            f"resource leak(s) detected ({len(failures)}):\n"
            + "\n\n".join(failures),
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _result_cache_isolation():
    """Result-cache isolation (core/resultcache.py): the store is
    process-global like DEVICE_CACHE, so entries, counters and the
    configured budget must not leak across tests — reset to defaults
    afterwards (the cache stays ENABLED suite-wide: every repeat query
    in the suite then exercises revalidation against the recompute the
    test asserts, which is free differential coverage)."""
    yield
    from pilosa_tpu.core import resultcache

    resultcache.RESULT_CACHE.reset()
    resultcache.RESULT_CACHE.configure(
        budget_bytes=resultcache.DEFAULT_BUDGET_BYTES, repair=True
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process E2E tests (boot real server processes)"
    )
