"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's analog is the
in-process multi-node cluster harness, /root/reference/test/pilosa.go:390
MustRunCluster). Real-TPU behavior is exercised by bench.py and the driver's
compile checks, not by the unit suite.

force_cpu must run before anything initializes a JAX backend — the hosted
environment's sitecustomize pre-registers a tunneled TPU backend that would
otherwise be dialed (and can hang) even for CPU-only tests.
"""

from pilosa_tpu.utils.cpuonly import force_cpu

force_cpu(8)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _fault_plane_leak_guard():
    """State-leak guard: a test that installs a process-global
    FaultInjector or BreakerRegistry (faults.install_injector /
    install_breakers) and forgets to uninstall it would silently poison
    every later test's internode traffic — fail loudly instead."""
    yield
    from pilosa_tpu.server import faults

    leaked = []
    if faults.global_injector() is not None:
        faults.uninstall_injector()
        leaked.append("FaultInjector")
    if faults.global_breakers() is not None:
        faults.uninstall_breakers()
        leaked.append("BreakerRegistry")
    if leaked:
        pytest.fail(
            f"test left a global {' and '.join(leaked)} installed "
            "(faults.uninstall_injector()/uninstall_breakers() missing)"
        )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process E2E tests (boot real server processes)"
    )
