"""Cross-request group-commit Count batching (exec/batcher.py).

VERDICT r4 #3: concurrent single-Count clients must share dispatches —
per-query system latency approaches RTT/N + device time instead of each
client paying the full round trip (the reference gives concurrent
requests no cross-request amortization; its worker pool only bounds
fan-out, executor.go:2559-2613)."""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.exec import batcher as batchmod
from pilosa_tpu.exec.batcher import CountBatcher
from pilosa_tpu.pql import parse
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _reset_stats():
    for k in batchmod.STATS:
        batchmod.STATS[k] = 0


class TestBatchable:
    def test_pure_counts(self):
        assert batchmod.batchable(parse("Count(Row(f=1))"))
        assert batchmod.batchable(
            parse("Count(Row(f=1))Count(Intersect(Row(f=1), Row(f=2)))")
        )

    def test_rejects_non_counts(self):
        assert not batchmod.batchable(parse("Row(f=1)"))
        assert not batchmod.batchable(parse("Count(Row(f=1))Row(f=2)"))
        assert not batchmod.batchable(parse("Set(1, f=1)"))
        assert not batchmod.batchable(parse("TopN(f, n=3)"))


class TestGroupCommit:
    def test_leader_runs_alone_immediately(self):
        b = CountBatcher()
        calls = []
        out = b.run("i", parse("Count(Row(f=1))"), lambda q: calls.append(q) or [7])
        assert out == [7]
        assert len(calls) == 1 and len(calls[0].calls) == 1

    @pytest.mark.skipif(
        os.environ.get("PILOSA_TPU_RACE_CHECK") == "1",
        reason="timing-window test: the two 50 ms sleep windows assume "
        "followers enqueue while the leader is held, and the race "
        "checker's per-access instrumentation can stretch follower "
        "startup past the window (observed flaky); the merge behavior "
        "is covered deterministically by the adaptive-hold tests",
    )
    def test_waiters_merge_into_one_execution(self):
        b = CountBatcher()
        release = threading.Event()
        execs = []

        def execute(q):
            execs.append(len(q.calls))
            if len(execs) == 1:
                release.wait(5)  # hold the leader so followers queue
            return list(range(len(q.calls)))

        results = {}

        def client(name):
            results[name] = b.run("i", parse("Count(Row(f=1))"), execute)

        leader = threading.Thread(target=client, args=("leader",))
        leader.start()
        time.sleep(0.05)  # leader is now inside execute()
        followers = [
            threading.Thread(target=client, args=(f"f{i}",)) for i in range(4)
        ]
        for t in followers:
            t.start()
        time.sleep(0.05)  # followers enqueued behind the busy leader
        release.set()
        leader.join(5)
        for t in followers:
            t.join(5)
        # leader ran alone; all 4 followers merged into ONE execution
        assert execs == [1, 4]
        assert results["leader"] == [0]
        for i in range(4):
            assert results[f"f{i}"] == [i]  # sliced back in queue order

    def test_promoted_leader_merges_own_query(self):
        """Arrivals during a batch round get served by a PROMOTED leader
        that merges its own query into the next round — under sustained
        load every round is a full batch, not leader-solo alternation."""
        b = CountBatcher()
        entered = [threading.Event(), threading.Event()]
        gates = [threading.Event(), threading.Event()]
        execs = []

        def execute(q):
            i = len(execs)
            execs.append(len(q.calls))
            if i < len(gates):
                entered[i].set()
                gates[i].wait(5)
            return list(range(len(q.calls)))

        results = {}

        def client(name):
            results[name] = b.run("i", parse("Count(Row(f=1))"), execute)

        def enqueue_until(n):
            # deterministically wait until n waiters sit in the queue
            for _ in range(500):
                with b._mu:
                    if len(b._queue.get("i", [])) >= n:
                        return
                time.sleep(0.005)
            raise AssertionError("waiters never queued")

        leader = threading.Thread(target=client, args=("L",))
        leader.start()
        assert entered[0].wait(5)  # leader inside exec 0
        ab = [threading.Thread(target=client, args=(n,)) for n in ("A", "B")]
        for t in ab:
            t.start()
        enqueue_until(2)  # A, B queued
        gates[0].set()  # leader finishes; round [A, B] starts (exec 1)
        assert entered[1].wait(5)
        cd = [threading.Thread(target=client, args=(n,)) for n in ("C", "D")]
        for t in cd:
            t.start()
        enqueue_until(2)  # C, D queued behind the running round
        gates[1].set()  # round [A, B] finishes -> C promoted
        for t in [leader] + ab + cd:
            t.join(5)
        # exec 2 must carry BOTH C and D (merged), not C solo then D
        assert execs == [1, 2, 2], execs
        assert results["C"] == [0] and results["D"] == [1]

    def test_error_isolation(self):
        b = CountBatcher()
        release = threading.Event()
        state = {"n": 0}

        def execute(q):
            state["n"] += 1
            if state["n"] == 1:
                release.wait(5)
                return [1]
            if any("boom" in c.children[0].args for c in q.calls):
                raise ValueError("boom")
            return [len(q.calls)] * len(q.calls)

        results, errors = {}, {}

        def client(name, pql):
            try:
                results[name] = b.run("i", parse(pql), execute)
            except ValueError as e:
                errors[name] = str(e)

        leader = threading.Thread(target=client, args=("L", "Count(Row(f=1))"))
        leader.start()
        time.sleep(0.05)
        good = threading.Thread(target=client, args=("good", "Count(Row(f=1))"))
        bad = threading.Thread(target=client, args=("bad", "Count(Row(boom=1))"))
        good.start()
        bad.start()
        time.sleep(0.05)
        release.set()
        for t in (leader, good, bad):
            t.join(5)
        # merged exec raised -> split: the good query still answers, only
        # the bad one errors
        assert results["good"] == [1]
        assert errors["bad"] == "boom"

    def test_batch_size_cap(self):
        b = CountBatcher()
        release = threading.Event()
        execs = []

        def execute(q):
            execs.append(len(q.calls))
            if len(execs) == 1:
                release.wait(5)
            return [0] * len(q.calls)

        threads = [
            threading.Thread(
                target=lambda: b.run("i", parse("Count(Row(f=1))"), execute)
            )
            for _ in range(batchmod.MAX_BATCH_CALLS + 10)
        ]
        threads[0].start()
        time.sleep(0.05)
        for t in threads[1:]:
            t.start()
        time.sleep(0.2)
        release.set()
        for t in threads:
            t.join(5)
        assert execs[0] == 1
        assert max(execs) <= batchmod.MAX_BATCH_CALLS
        # padding rounds batches up to pow2, so total calls executed can
        # exceed the real query count but never by more than 2x
        assert batchmod.MAX_BATCH_CALLS + 10 <= sum(execs) <= 2 * (
            batchmod.MAX_BATCH_CALLS + 10
        )

    def test_pow2_padding_uses_noop_lanes(self):
        """Satellite: pad lanes are zero-row no-ops (Count(Difference())
        -> PZero), NOT repeats of the last real call — repeating a heavy
        call wasted up to ~2x device work on odd batch sizes. Pads are
        masked out: every waiter gets exactly its own results."""
        b = CountBatcher()
        release = threading.Event()
        merged_queries = []

        def execute(q):
            merged_queries.append(q)
            if len(merged_queries) == 1:
                release.wait(5)
            return list(range(len(q.calls)))

        threads = [
            threading.Thread(
                target=lambda: b.run("i", parse("Count(Row(f=1))"), execute)
            )
        ]
        threads[0].start()
        time.sleep(0.05)  # let it take leadership and block in execute
        outs = []
        for _ in range(3):  # 3 waiters -> merged round of 3, padded to 4
            th = threading.Thread(
                target=lambda: outs.append(
                    b.run("i", parse("Count(Row(f=1))"), execute)
                )
            )
            th.start()
            threads.append(th)
        time.sleep(0.1)
        release.set()
        for th in threads:
            th.join(5)
        merged = next(q for q in merged_queries if len(q.calls) == 4)
        real, pad = merged.calls[:3], merged.calls[3]
        assert all(c.name == "Count" for c in merged.calls)
        assert all(c.children[0].name == "Row" for c in real)
        # the pad lane is the zero-row no-op, not a repeat of a real call
        assert pad.children[0].name == "Difference"
        assert not pad.children[0].children
        # pads masked out: each waiter saw exactly one (its own) result
        assert sorted(len(o) for o in outs) == [1, 1, 1]

    def test_noop_pad_call_counts_zero_end_to_end(self):
        """The pad lane must execute as a true no-op on the real
        executor: Count(Difference()) == 0 whatever data exists."""
        from pilosa_tpu.core.field import FieldOptions
        from pilosa_tpu.core.holder import Holder
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.pql import Query

        h = Holder().open()
        idx = h.create_index("padx")
        f = idx.create_field("f", FieldOptions())
        f.set_bit(1, 7)
        ex = Executor(h)
        pad = batchmod._noop_pad_call()
        assert ex.execute("padx", Query(calls=[pad])) == [0]
        # and merged next to a real call, results stay position-correct
        got = ex.execute(
            "padx", Query(calls=[parse("Count(Row(f=1))").calls[0], pad])
        )
        assert got == [1, 0]

    def test_indexes_batch_independently(self):
        b = CountBatcher()
        release = threading.Event()
        execs = []

        def execute(q):
            execs.append(len(q.calls))
            if len(execs) == 1:
                release.wait(5)
            return [0] * len(q.calls)

        t1 = threading.Thread(target=lambda: b.run("a", parse("Count(Row(f=1))"), execute))
        t1.start()
        time.sleep(0.05)
        # different index: must NOT queue behind index a's leader
        out = b.run("b", parse("Count(Row(f=1))"), lambda q: [42])
        assert out == [42]
        release.set()
        t1.join(5)


class TestEndToEnd:
    @pytest.fixture()
    def server(self):
        srv = NodeServer(None, "batch-test")
        srv.start()
        yield srv
        srv.stop()

    def test_concurrent_clients_share_dispatches(self, server):
        api = server.api
        api.create_index("bi")
        api.create_field("bi", "f")
        idx = server.holder.index("bi")
        f = idx.field("f")
        rng = np.random.default_rng(5)
        for row in (1, 2):
            cols = rng.integers(0, 4 * SHARD_WIDTH, 5000).astype(np.uint64)
            f.import_bits(np.full(len(cols), row, np.uint64), cols)
        q = "Count(Intersect(Row(f=1), Row(f=2)))"
        (expect,) = api.query("bi", q)  # warm + truth
        # overlap is timing-dependent, so retry the round until at least
        # one batch forms (locked STATS make the totals exact per round)
        for _ in range(5):
            _reset_stats()
            results = []
            errs = []

            def client():
                try:
                    for _ in range(3):
                        results.append(api.query("bi", q)[0])
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=client) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
            assert not errs
            assert results == [expect] * 24
            s = batchmod.STATS
            assert s["leader"] + s["batched"] == 24
            assert s["fallback_splits"] == 0
            if s["batched"] >= 1:
                break
        assert s["leader"] >= 1
        assert s["batched"] >= 1  # some clients coalesced

    def test_batched_counts_survive_node_failover(self):
        """Concurrent batched Counts against a replicated cluster keep
        answering correctly while a node dies mid-stream: the merged
        executions fan out through the distributed executor, which
        re-maps dead owners to live replicas; a failing merged exec
        splits per-query rather than poisoning batchmates."""
        from pilosa_tpu.testing import ClusterHarness

        with ClusterHarness(3, replica_n=2, in_memory=True) as cluster:
            api = cluster[0].api
            api.create_index("fi")
            api.create_field("fi", "f")
            rng = np.random.default_rng(12)
            cols = rng.integers(0, 6 * SHARD_WIDTH, 2500).astype(np.uint64)
            q = "".join(f"Set({int(c)}, f=1)" for c in cols[:400])
            api.query("fi", q)
            expect = len({int(c) for c in cols[:400]})
            qc = "Count(Row(f=1))"
            assert api.query("fi", qc)[0] == expect  # warm
            stop_at = threading.Event()
            errs, got = [], []

            def client():
                try:
                    for i in range(6):
                        got.append(api.query("fi", qc)[0])
                        if i == 1:
                            stop_at.set()
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=client) for _ in range(6)]
            for t in threads:
                t.start()
            assert stop_at.wait(10)
            cluster.stop_node(2)  # mid-stream kill; replicas hold the data
            for t in threads:
                t.join(30)
            assert not errs, errs[:1]
            assert got == [expect] * 36

    def test_non_count_queries_bypass(self, server):
        api = server.api
        api.create_index("bj")
        api.create_field("bj", "f")
        api.query("bj", "Set(1, f=1)Set(9, f=1)")
        _reset_stats()
        (row,) = api.query("bj", "Row(f=1)")
        assert sorted(int(c) for c in row.columns()) == [1, 9]
        assert batchmod.STATS["leader"] == 0  # never entered the batcher


class TestBatchSizeStat:
    def test_solo_round_records_one(self):
        from pilosa_tpu.utils.stats import StatsClient

        b = CountBatcher()
        st = StatsClient()
        b.stats = st
        b.run("i", parse("Count(Row(f=1))"), lambda q: [1])
        hist = st.registry.snapshot().get("batcher.batch_size")
        assert hist is not None and hist["count"] == 1 and hist["max"] == 1

    def test_merged_round_records_total_calls(self):
        from pilosa_tpu.utils.stats import StatsClient

        b = CountBatcher()
        st = StatsClient()
        b.stats = st
        release = threading.Event()
        started = threading.Event()

        def execute(q):
            started.set()
            if not release.is_set():
                release.wait(5)
            return list(range(len(q.calls)))

        results = {}

        def follower(i):
            results[i] = b.run("i", parse("Count(Row(f=2))"), execute)

        leader = threading.Thread(
            target=lambda: b.run("i", parse("Count(Row(f=1))"), execute),
            daemon=True,
        )
        leader.start()
        started.wait(5)
        followers = [
            threading.Thread(target=follower, args=(i,), daemon=True)
            for i in range(3)
        ]
        for th in followers:
            th.start()
        # wait for all three to be queued behind the leader
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with b._mu:
                if len(b._queue.get("i", ())) == 3:
                    break
            time.sleep(0.002)
        release.set()
        leader.join(5)
        for th in followers:
            th.join(5)
        hist = st.registry.snapshot()["batcher.batch_size"]
        assert hist["max"] >= 3  # the merged follower round
        assert all(len(r) == 1 for r in results.values())

    def test_run_builds_its_queue_as_a_deque(self):
        """The waiter queue created by run() itself must be a deque —
        the list-as-queue pop(0) was O(n) per dequeue (satellite fix)."""
        from collections import deque

        b = CountBatcher()
        release = threading.Event()
        started = threading.Event()

        def execute(q):
            started.set()
            release.wait(5)
            return list(range(len(q.calls)))

        leader = threading.Thread(
            target=lambda: b.run("i", parse("Count(Row(f=1))"), execute),
            daemon=True,
        )
        leader.start()
        started.wait(5)
        follower = threading.Thread(
            target=lambda: b.run("i", parse("Count(Row(f=2))"), execute),
            daemon=True,
        )
        follower.start()
        deadline = time.monotonic() + 5
        queue_obj = None
        while time.monotonic() < deadline:
            with b._mu:
                queue_obj = b._queue.get("i")
                if queue_obj is not None and len(queue_obj) == 1:
                    break
            time.sleep(0.002)
        assert isinstance(queue_obj, deque), type(queue_obj)
        release.set()
        leader.join(5)
        follower.join(5)
