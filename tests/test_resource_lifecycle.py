"""Resource-lifecycle machinery: CFG shapes, the runtime ledger, and
regression tests for the leaks the static pass found.

Three halves of the same gate (docs/development.md "Resource ownership
contracts"):

1. analysis/cfg.py — the statement-level CFG the must-release pass
   walks. Each test pins one control-flow shape's edge structure
   (finally clones, exception edges, loop exits, with-unwind), because
   a missing edge silently turns a real leak into a clean report.
2. utils/resources.py — the runtime ledger behind the autouse conftest
   guard: balances + acquisition stacks under PILOSA_TPU_RESOURCE_CHECK,
   always-on probes, cheap passthrough otherwise.
3. The error-path leak fixes themselves (hbm/residency.py staging pins,
   server/node.py capture lease registration, exec/distributed.py
   fan-out pool), each exercised through its real failure injection.

Rule-level seeded-violation coverage for RES001-RES005 lives in
test_static_analysis.py.
"""

import ast
import textwrap

import numpy as np
import pytest

from pilosa_tpu.analysis.cfg import build_cfg
from pilosa_tpu.core.devcache import DEVICE_CACHE
from pilosa_tpu.core.fragment import Fragment, TransferCaptureLost
from pilosa_tpu.hbm import residency as hbm_res
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.utils import resources

# ---------------------------------------------------------------------------
# CFG shapes
# ---------------------------------------------------------------------------


def fn_cfg(src: str):
    tree = ast.parse(textwrap.dedent(src))
    fn = next(n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef))
    return build_cfg(fn)


def reach(cfg, start: int) -> set:
    """Node ids reachable from `start` over succ+exc edges."""
    seen, work = {start}, [start]
    while work:
        for m in cfg.node(work.pop()).edges():
            if m not in seen:
                seen.add(m)
                work.append(m)
    return seen


def lines(cfg, nids) -> set:
    return {cfg.node(n).line for n in nids}


def node_at(cfg, line: int, kind: str = None):
    hits = [
        n
        for n in cfg.nodes
        if n.line == line and (kind is None or n.kind == kind)
    ]
    assert hits, f"no node at line {line} (kind={kind})"
    return hits[0]


class TestCfgShapes:
    def test_try_finally_runs_on_normal_and_raise_paths(self):
        cfg = fn_cfg(
            """
            def f(work, cleanup):
                try:
                    work()
                finally:
                    cleanup()
            """
        )
        # the finally body is cloned per unwind kind: the cleanup()
        # statement appears in more than one node
        cleanups = [n for n in cfg.nodes if n.line == 6 and n.kind == "stmt"]
        assert len(cleanups) >= 2
        # the raising path out of work() goes THROUGH a cleanup clone
        work = node_at(cfg, 4, "stmt")
        assert work.exc, "work() must have an exception edge"
        assert all(cfg.node(t).line == 6 for t in work.exc)
        # both terminals are reachable, each via a cleanup node
        assert cfg.exit in reach(cfg, cfg.entry)
        assert cfg.raise_exit in reach(cfg, cfg.entry)

    def test_except_edge_catch_all_stops_escape(self):
        cfg = fn_cfg(
            """
            def f(work):
                try:
                    work()
                except BaseException:
                    x = 1
            """
        )
        # the only raiser is caught by a catch-all: no escape at all
        assert cfg.raise_exit not in reach(cfg, cfg.entry)

    def test_except_edge_narrow_handler_still_escapes(self):
        cfg = fn_cfg(
            """
            def f(work):
                try:
                    work()
                except ValueError:
                    x = 1
            """
        )
        work = node_at(cfg, 4, "stmt")
        assert work.exc
        # a ValueError handler doesn't catch everything: the dispatch
        # keeps an escape route to the raise exit
        escape = reach(cfg, next(iter(work.exc)))
        assert cfg.raise_exit in escape
        # ... and the handler body is also reachable from the dispatch
        assert 6 in lines(cfg, escape)

    def test_loop_break_jumps_past_the_body(self):
        cfg = fn_cfg(
            """
            def f(xs, body, tail):
                for x in xs:
                    if x:
                        break
                    body()
                tail()
            """
        )
        brk = next(
            n for n in cfg.nodes if isinstance(n.stmt, ast.Break)
        )
        after = reach(cfg, brk.nid)
        assert 7 in lines(cfg, after)  # tail() runs
        assert 6 not in lines(cfg, after)  # body() skipped
        # break exits through the loop's join node, not the loop head
        assert all(cfg.node(t).kind == "loop_exit" for t in brk.succ)

    def test_early_return_goes_straight_to_exit(self):
        cfg = fn_cfg(
            """
            def f(flag, rest):
                if flag:
                    return 1
                rest()
            """
        )
        ret = next(n for n in cfg.nodes if isinstance(n.stmt, ast.Return))
        assert ret.succ == {cfg.exit}
        assert 5 not in lines(cfg, reach(cfg, ret.nid))

    def test_with_unwinds_through_exit_on_raise(self):
        cfg = fn_cfg(
            """
            def f(cm, work, tail):
                with cm() as h:
                    work()
                tail()
            """
        )
        work = node_at(cfg, 4, "stmt")
        assert work.exc
        # the exception edge lands on a with_exit clone (__exit__ runs),
        # and from there only the raise exit is reachable — not tail()
        for t in work.exc:
            assert cfg.node(t).kind == "with_exit"
            unwound = reach(cfg, t)
            assert cfg.raise_exit in unwound
            assert 5 not in lines(cfg, unwound)
        # the normal path still goes through a (different) with_exit
        normal = reach(cfg, next(iter(work.succ)))
        assert 5 in lines(cfg, normal)

    def test_identity_test_has_no_exception_edge(self):
        cfg = fn_cfg(
            """
            def f(x):
                if x is not None:
                    x.close()
            """
        )
        assert not node_at(cfg, 3, "branch").exc

    def test_equality_test_does_have_an_exception_edge(self):
        cfg = fn_cfg(
            """
            def f(x):
                if x == 0:
                    return 1
            """
        )
        assert node_at(cfg, 3, "branch").exc


# ---------------------------------------------------------------------------
# the runtime ledger
# ---------------------------------------------------------------------------


@pytest.fixture
def ledger():
    """Ledger enabled for the test, restored (and drained) after — the
    autouse conftest guard must see a clean slate either way."""
    was = resources.enabled()
    resources.drain()
    resources.enable()
    yield resources
    resources.drain()
    if not was:
        resources.disable()


class TestResourceLedger:
    def test_balance_round_trip(self, ledger):
        ledger.acquire("hbm.pin", ("k", 1))
        ledger.acquire("hbm.pin", ("k", 1))  # refcount: two holds, one token
        ledger.acquire("hbm.pin", ("k", 2))
        assert ledger.balance("hbm.pin") == 3
        ledger.release("hbm.pin", ("k", 1))
        assert ledger.balance("hbm.pin") == 2
        ledger.release("hbm.pin", ("k", 1))
        ledger.release("hbm.pin", ("k", 2))
        assert ledger.balance("hbm.pin") == 0
        assert ledger.balances() == {}

    def test_unmatched_release_is_ignored_not_negative(self, ledger):
        ledger.release("hbm.pin", ("never", "acquired"))
        assert ledger.balance("hbm.pin") == 0
        ledger.acquire("hbm.pin", "t")
        ledger.release("hbm.pin", "t")
        ledger.release("hbm.pin", "t")  # idempotent second release
        assert ledger.balance("hbm.pin") == 0

    def test_outstanding_carries_acquisition_stacks(self, ledger):
        ledger.acquire("sched.ticket", 42)
        ((cls, token, stack),) = ledger.outstanding("sched.ticket")
        assert (cls, token) == ("sched.ticket", 42)
        # the stack points at THIS test, not at the ledger internals
        assert "test_outstanding_carries_acquisition_stacks" in stack
        ledger.release("sched.ticket", 42)

    def test_check_and_reset_reports_then_clears(self, ledger):
        ledger.acquire("fragment.capture", "tag")
        failures = ledger.check_and_reset()
        assert any(
            "fragment.capture" in f and "balance=1" in f for f in failures
        ), failures
        assert ledger.balances() == {}  # reported leaks are cleared
        assert not [
            f for f in ledger.check_and_reset() if "imbalance" in f
        ]

    def test_disabled_ledger_records_nothing(self):
        was = resources.enabled()
        resources.disable()
        try:
            resources.acquire("hbm.pin", "cheap")
            assert resources.balance("hbm.pin") == 0
            assert resources.outstanding() == []
        finally:
            if was:
                resources.enable()

    def test_probe_for_undeclared_class_rejected(self):
        with pytest.raises(ValueError):
            resources.register_probe("not.a.class", lambda: [])

    def test_probes_run_even_when_disabled(self):
        was = resources.enabled()
        resources.disable()
        resources.register_probe("runtime.pool", lambda: ["pool probe hit"])
        try:
            assert "pool probe hit" in resources.check_and_reset()
        finally:
            resources._probes.pop("runtime.pool", None)
            if was:
                resources.enable()

    def test_static_contracts_match_ledger_registry(self):
        # RES005 in miniature: the import-time registries really are in
        # lockstep (the gate test covers the parsed-source version)
        from pilosa_tpu.analysis.lifecycle import CONTRACTS

        assert {c.resource for c in CONTRACTS} == set(
            resources.RESOURCE_CLASSES
        )


# ---------------------------------------------------------------------------
# the leaks the pass found (regression: each via its real failure path)
# ---------------------------------------------------------------------------


@pytest.fixture
def staging_env():
    """Single-device staging with clean cache state, like test_hbm's
    paging_env but scoped to the leak regressions."""
    old_mesh = pmesh.active_mesh()
    pmesh.set_active_mesh(None)
    old_rows = hbm_res.extent_rows()
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    yield
    hbm_res.configure(extent_rows=old_rows)
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    pmesh.set_active_mesh(old_mesh)


class TestLeakRegressions:
    def test_monolithic_stage_unpins_when_accounting_raises(
        self, staging_env, monkeypatch
    ):
        """residency._stage_inner (monolithic): a raise in _note_upload
        used to leave the freshly built entry pinned forever."""
        hbm_res.configure(extent_rows=0)  # force the monolithic path

        def boom(*a, **k):
            raise RuntimeError("accounting exploded")

        monkeypatch.setattr(hbm_res, "_note_upload", boom)
        build = lambda lo, hi: np.zeros((hi - lo, 8), np.uint32)  # noqa: E731
        with pytest.raises(RuntimeError, match="accounting exploded"):
            hbm_res.stage_row_stack(("leak", "mono"), 2, build)
        assert DEVICE_CACHE.pinned_bytes == 0

    def test_extent_stage_unpins_when_assembly_raises(self, staging_env):
        """residency._stage_inner (multi-extent): a raise in the final
        concatenate used to strand every staged extent pinned when no
        ExtentTable was passed."""
        hbm_res.configure(extent_rows=1)

        def ragged(lo, hi):
            # per-extent widths differ -> concatenate along axis 0 fails
            return np.zeros((hi - lo, 8 + lo), np.uint32)

        with pytest.raises((ValueError, TypeError)):
            hbm_res.stage_row_stack(("leak", "ragged"), 2, ragged)
        assert DEVICE_CACHE.pinned_bytes == 0

    def test_capture_disarmed_when_lease_registration_fails(
        self, monkeypatch
    ):
        """node.begin_fragment_capture: a raise between arming the
        capture and registering its lease used to leave the capture
        buffering writes forever — no lease to expire it, no entry to
        drain it."""
        srv = NodeServer(None, "capreg-leak-test")
        try:
            frag = Fragment(None, "i", "f", "standard", 0).open()

            def boom(now):
                raise RuntimeError("sweep exploded")

            monkeypatch.setattr(srv, "_sweep_captures_locked", boom)
            with pytest.raises(RuntimeError, match="sweep exploded"):
                srv.begin_fragment_capture(
                    "j:dest", ("i", "f", "standard", 0), frag
                )
            assert srv._transfer_captures == {}
            with pytest.raises(TransferCaptureLost):
                frag.drain_capture("j:dest")  # disarmed, not buffering
        finally:
            srv.stop()

    def test_node_stop_closes_the_fanout_pool(self):
        """DistributedExecutor: the lazy fan-out pool used to outlive
        its server — every start/stop cycle stranded idle threads."""
        srv = NodeServer(None, "poolclose-test")
        try:
            pool = srv.executor._fanout_pool()
            assert srv.executor._pool is pool
        finally:
            srv.stop()
        assert srv.executor._pool is None
        with pytest.raises(RuntimeError):
            pool.submit(print)  # shut down: rejects new work
        srv.executor.close()  # idempotent
