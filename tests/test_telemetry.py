"""Cluster telemetry plane tests (ISSUE 8): per-index resource
attribution, federated metrics rollup, utilization timeline.

Layers: histogram bucket-wise merge property tests (merge of N node
histograms is IDENTICAL to one histogram fed the union of samples);
registry export/merge units; label GC (create/delete 100 indexes
returns the series count to baseline); per-index HBM attribution
reconciling byte-for-byte with the global devcache ledger under
eviction pressure; the statsd preboot buffer; prom-lint labeled-family
rules on seeded violations; and the 3-node acceptance scenario —
exact per-index counter merge, a seeded slow node pulling the cluster
p99 up, and a killed peer degrading /cluster/overview to stale-marked
data instead of a 500."""

import json
import math
import socket
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DEVICE_CACHE
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.hbm import residency as hbm_res
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW
from pilosa_tpu.testing import ClusterHarness
from pilosa_tpu.utils import stats as statsmod
from pilosa_tpu.utils.stats import (
    HIST_BOUNDS,
    Histogram,
    Registry,
    _StatsdTransport,
)

from tools.prom_lint import lint


def http_json(method, url, body=None, headers=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def _seed(api, index, n_shards=3, rows=2):
    api.create_index(index)
    api.create_field(index, "f", {"type": "set"})
    rws, cols = [], []
    for s in range(n_shards):
        for r in range(rows):
            for k in range(20):
                rws.append(r)
                cols.append(s * SHARD_WIDTH + 17 * k + r)
    api.import_bits(index, "f", rws, cols)


# ---------------------------------------------------------------------------
# histogram merge: the property the whole federation rests on
# ---------------------------------------------------------------------------


class TestHistogramMerge:
    def test_merge_identical_to_union(self):
        """Bucket-wise merge of N per-node histograms must be EXACTLY
        the histogram of the union of their samples — same buckets,
        count, min/max; sum within float addition reordering."""
        rng = np.random.default_rng(7)
        per_node = [
            list(rng.lognormal(mean, 1.2, size=n))
            for mean, n in ((0.0, 400), (2.0, 150), (4.5, 37))
        ]
        nodes = []
        union = Histogram()
        for samples in per_node:
            h = Histogram()
            for v in samples:
                h.observe(v)
                union.observe(v)
            nodes.append(h)
        merged = Histogram()
        for h in nodes:
            assert merged.merge_dict(h.export_dict())
        assert merged.buckets == union.buckets
        assert merged.count == union.count == sum(len(s) for s in per_node)
        assert merged.total == pytest.approx(union.total, rel=1e-12)
        assert merged.vmin == union.vmin
        assert merged.vmax == union.vmax
        # the Prometheus exposition series are therefore identical too
        assert merged.cumulative() == union.cumulative()

    def test_merged_quantiles_within_interpolation_tolerance(self):
        """Quantiles of the merged histogram track the true sample
        quantiles to within one log-bucket (bounds step at most 2.5x)."""
        rng = np.random.default_rng(11)
        per_node = [list(rng.lognormal(1.0, 1.0, size=300)) for _ in range(4)]
        merged = Histogram()
        for samples in per_node:
            h = Histogram()
            for v in samples:
                h.observe(v)
            merged.merge_dict(h.export_dict())
        flat = np.sort(np.concatenate(per_node))
        for q in (0.5, 0.95, 0.99):
            est = merged.quantile(q)
            true = float(np.quantile(flat, q))
            assert true / 2.6 <= est <= true * 2.6, (q, est, true)

    def test_mismatched_bucket_layout_rejected(self):
        """A mixed-version peer with different bounds must be skipped,
        never mis-merged."""
        h = Histogram()
        h.observe(3.0)
        before = list(h.buckets)
        assert not h.merge_dict({"buckets": [1] * 4, "count": 1, "sum": 9.0})
        assert not h.merge_dict({"buckets": "nope", "count": 5})
        assert not h.merge_dict({"count": 0, "buckets": [0] * len(h.buckets)})
        assert h.buckets == before and h.count == 1

    def test_one_slow_node_pulls_merged_p99_up(self):
        """The seeded-skew property: two fast nodes with tight
        distributions plus one slow node — the merged p99 must land in
        the slow regime even though 2/3 of nodes report fast p99s."""
        fast_a, fast_b, slow = Histogram(), Histogram(), Histogram()
        for _ in range(500):
            fast_a.observe(2.0)
            fast_b.observe(3.0)
        for _ in range(40):  # >1% of the merged population
            slow.observe(4000.0)
        merged = Histogram()
        for h in (fast_a, fast_b, slow):
            merged.merge_dict(h.export_dict())
        assert fast_a.quantile(0.99) < 10
        assert fast_b.quantile(0.99) < 10
        assert merged.quantile(0.99) > 1000


class TestRegistryFederation:
    def test_export_merge_sums_counters_and_gauges(self):
        a, b, merged = Registry(), Registry(), Registry()
        a.count("query_n", 3, ("index:t1",))
        b.count("query_n", 4, ("index:t1",))
        b.count("query_n", 9, ("index:t2",))
        a.gauge("sched.inflight_bytes", 100, ())
        b.gauge("sched.inflight_bytes", 50, ())
        a.add_to_set("uniq", "x", ())
        b.add_to_set("uniq", "y", ())
        merged.merge_state(a.export_state())
        merged.merge_state(b.export_state())
        snap = merged.snapshot()
        assert snap["query_n;index:t1"] == 7
        assert snap["query_n;index:t2"] == 9
        assert snap["sched.inflight_bytes"] == 150
        assert snap["uniq"] == 2  # set series merge by cardinality

    def test_merge_state_skips_malformed_entries(self):
        merged = Registry()
        merged.merge_state(
            {
                "counters": [["ok", [], 1], ["bad"], ["bad2", [], "x"]],
                "gauges": [[1, 2]],
                "hists": [["h", [], "not-a-dict"], "junk"],
            }
        )
        assert merged.snapshot() == {"ok": 1.0}

    def test_merge_state_skips_garbled_histogram_payloads(self):
        """A half-written snapshot (non-numeric bucket or count) must be
        skipped whole — no raise out of the /cluster/* merge, no
        partially-updated accumulator, no phantom empty series."""
        h = Histogram()
        h.observe(3.0)
        good = h.export_dict()
        bad_bucket = dict(good, buckets=[*good["buckets"]])
        bad_bucket["buckets"][0] = "x"
        merged = Registry()
        merged.merge_state(
            {
                "hists": [
                    ["h", [], bad_bucket],
                    ["h", [], dict(good, count="nope")],
                    ["h", [], good],
                ]
            }
        )
        # only the clean payload landed, and it landed exactly once
        assert merged.quantile("h", 0.5, ()) > 0
        snap = merged.snapshot()
        assert snap["h"]["count"] == 1
        # the garbled-only series never materialized
        merged2 = Registry()
        merged2.merge_state({"hists": [["solo", [], bad_bucket]]})
        assert merged2.snapshot() == {}

    def test_drop_label_removes_every_series_kind(self):
        reg = Registry()
        reg.count("query_n", 1, ("index:gone",))
        reg.gauge("hbm.resident_bytes", 5, ("index:gone",))
        reg.observe("query_ms", 1.0, ("index:gone",))
        reg.add_to_set("uniq", "x", ("index:gone",))
        reg.count("query_n", 1, ("index:kept",))
        assert reg.drop_label("index", "gone") == 4
        snap = reg.snapshot()
        assert snap == {"query_n;index:kept": 1.0}


# ---------------------------------------------------------------------------
# prom-lint labeled-family rules (STAT_LABELS)
# ---------------------------------------------------------------------------


class TestPromLintLabels:
    LABELS = {"query_ms": ("index",), "sched.admit": ("class", "index")}

    def _lint(self, text):
        return lint(
            text,
            declared={"query_ms", "sched.admit", "plain"},
            declared_prefixes=set(),
            labels=self.LABELS,
        )

    def test_clean_labeled_exposition(self):
        text = (
            "# TYPE pilosa_tpu_sched_admit counter\n"
            'pilosa_tpu_sched_admit{class="interactive",index="a"} 3\n'
            'pilosa_tpu_sched_admit{class="batch",index="-"} 1\n'
            "# TYPE pilosa_tpu_plain gauge\n"
            "pilosa_tpu_plain 5\n"
        )
        assert self._lint(text) == []

    def test_dropped_label_key_flagged(self):
        text = (
            "# TYPE pilosa_tpu_sched_admit counter\n"
            'pilosa_tpu_sched_admit{class="interactive"} 3\n'
        )
        errs = self._lint(text)
        assert any("missing ['index']" in e for e in errs)

    def test_unlabeled_series_mixed_into_labeled_family_flagged(self):
        text = (
            "# TYPE pilosa_tpu_query_ms histogram\n"
            'pilosa_tpu_query_ms_bucket{index="a",le="+Inf"} 2\n'
            'pilosa_tpu_query_ms_sum{index="a"} 3.0\n'
            'pilosa_tpu_query_ms_count{index="a"} 2\n'
            'pilosa_tpu_query_ms_bucket{le="+Inf"} 1\n'
            "pilosa_tpu_query_ms_sum 1.0\n"
            "pilosa_tpu_query_ms_count 1\n"
        )
        errs = self._lint(text)
        assert any("violates its STAT_LABELS key set" in e for e in errs)

    def test_le_is_not_a_label(self):
        text = (
            "# TYPE pilosa_tpu_query_ms histogram\n"
            'pilosa_tpu_query_ms_bucket{index="a",le="1"} 2\n'
            'pilosa_tpu_query_ms_bucket{index="a",le="+Inf"} 2\n'
            'pilosa_tpu_query_ms_sum{index="a"} 1.2\n'
            'pilosa_tpu_query_ms_count{index="a"} 2\n'
        )
        assert self._lint(text) == []

    def test_unlisted_family_with_labels_flagged(self):
        text = (
            "# TYPE pilosa_tpu_plain gauge\n"
            'pilosa_tpu_plain{index="a"} 5\n'
        )
        errs = self._lint(text)
        assert any("not declared in STAT_LABELS" in e for e in errs)

    def test_undeclared_extra_label_flagged(self):
        text = (
            "# TYPE pilosa_tpu_sched_admit counter\n"
            'pilosa_tpu_sched_admit{class="batch",index="a",shard="0"} 3\n'
        )
        errs = self._lint(text)
        assert any("undeclared ['shard']" in e for e in errs)


def test_stat_labels_documented_in_observability_doc():
    """Doc-side half of the labeled-family contract: every STAT_LABELS
    family and each of its label keys appears in docs/observability.md."""
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs",
        "observability.md",
    )
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    for family, keys in statsmod.STAT_LABELS.items():
        assert family in text, f"STAT_LABELS family {family!r} undocumented"
        for k in keys:
            assert k in text


# ---------------------------------------------------------------------------
# statsd preboot buffering (satellite: early-boot observations must not
# silently vanish before the backend's DNS resolves)
# ---------------------------------------------------------------------------


class _FakeSock:
    def __init__(self):
        self.sent = []

    def sendto(self, datagram, addr):
        self.sent.append(datagram)

    def close(self):
        pass


class TestStatsdPreboot:
    def test_buffers_until_resolution_then_flushes_in_order(
        self, monkeypatch
    ):
        reg = Registry()
        fails = {"n": 2}
        real_getaddrinfo = socket.getaddrinfo

        def flaky(host, port, **kw):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise socket.gaierror("not yet")
            return real_getaddrinfo("127.0.0.1", port, **kw)

        monkeypatch.setattr(socket, "getaddrinfo", flaky)
        monkeypatch.setattr(_StatsdTransport, "RESOLVE_RETRY", 0.0)
        sock = _FakeSock()
        # construction burns failed resolve #1
        t = _StatsdTransport("statsd.sidecar:8125", reg, sock=sock)
        t.send(b"a:1|c")  # second failed resolve -> buffered
        assert sock.sent == []
        t.send(b"b:1|c")  # resolves: buffer flushes first, in order
        t.send(b"c:1|c")
        assert sock.sent == [b"a:1|c", b"b:1|c", b"c:1|c"]
        assert reg.snapshot() == {}  # nothing was dropped

    def test_overflow_and_close_count_dropped_preboot(self, monkeypatch):
        def never(host, port, **kw):
            raise socket.gaierror("no such host")

        monkeypatch.setattr(socket, "getaddrinfo", never)
        reg = Registry()
        t = _StatsdTransport("statsd.sidecar:8125", reg, sock=_FakeSock())
        monkeypatch.setattr(t, "BUFFER_MAX", 8)
        for i in range(11):  # 3 over the buffer bound: drop-oldest
            t.send(b"x:%d|c" % i)
        assert reg.snapshot()["stats.dropped_preboot"] == 3
        t.close()  # 8 still-buffered datagrams are lost too
        assert reg.snapshot()["stats.dropped_preboot"] == 11
        t.send(b"late:1|c")  # after close: ignored, not counted
        assert reg.snapshot()["stats.dropped_preboot"] == 11


# ---------------------------------------------------------------------------
# per-index HBM attribution reconciles with the global ledger
# ---------------------------------------------------------------------------


@pytest.fixture
def paging_env():
    old_mesh = pmesh.active_mesh()
    pmesh.set_active_mesh(None)
    old_budget = DEVICE_CACHE.budget_bytes
    old_rows = hbm_res.extent_rows()
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    yield
    hbm_res.configure(extent_rows=old_rows)
    DEVICE_CACHE.budget_bytes = old_budget
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    pmesh.set_active_mesh(old_mesh)


class TestHbmAttribution:
    def _two_tenant_holder(self, n_rows, n_shards):
        h = Holder().open()
        rng = np.random.default_rng(5)
        for name in ("ten_a", "ten_b"):
            idx = h.create_index(name)
            f = idx.create_field("f", FieldOptions())
            for r in range(n_rows):
                for s in range(n_shards):
                    f.import_row_words(
                        r,
                        s,
                        rng.integers(0, 2**32, WORDS_PER_ROW).astype(
                            np.uint32
                        ),
                    )
        return Executor(h), h

    def test_per_index_bytes_reconcile_under_eviction_pressure(
        self, paging_env
    ):
        """Acceptance: sum of per-index resident bytes == the global
        devcache ledger byte-for-byte while two tenants fight over a
        budget below their combined working set (evictions churning the
        attribution map must never desync it)."""
        row_bytes = WORDS_PER_ROW * 4
        S, EXT_ROWS, N_ROWS = 8, 2, 6
        hbm_res.configure(extent_rows=EXT_ROWS)
        stack_bytes = S * row_bytes
        ws_one = N_ROWS * stack_bytes  # one tenant's working set
        DEVICE_CACHE.budget_bytes = int(1.5 * ws_one)  # < 2 tenants
        ex, _h = self._two_tenant_holder(N_ROWS, S)
        q = (
            "Count(Union("
            + ", ".join(f"Row(f={r})" for r in range(N_ROWS))
            + "))"
        )

        def reconcile():
            by_index = DEVICE_CACHE.index_resident_bytes()
            assert sum(by_index.values()) == DEVICE_CACHE.bytes_used
            return by_index

        for idx in ("ten_a", "ten_b", "ten_a", "ten_b", "ten_a"):
            ex.execute(idx, q)
            by_index = reconcile()
            # the tenant that just ran is resident
            assert by_index.get(idx, 0) > 0
        snap = hbm_res.stats_snapshot()
        # eviction pressure actually happened (budget < combined ws)
        assert snap["evicted_extent_bytes"] > 0
        # restage attribution splits the cumulative bill across tenants
        per_idx = snap["restage_by_index"]
        assert set(per_idx) >= {"ten_a", "ten_b"}
        assert sum(per_idx.values()) == snap["restage_bytes"]

    def test_gauge_path_reconciles_on_a_live_node(self, paging_env):
        """Through the server funnel: publish_cache_gauges' per-index
        hbm.resident_bytes series sum to devcache.resident_bytes."""
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            _seed(srv.api, "ga", n_shards=2)
            _seed(srv.api, "gb", n_shards=2)
            for idx in ("ga", "gb", "ga"):
                srv.api.query(idx, "Count(Row(f=0))")
            srv.publish_cache_gauges()
            snap = srv.stats.registry.snapshot()
            per_index = {
                k: v
                for k, v in snap.items()
                if k.startswith("hbm.resident_bytes;")
            }
            assert per_index, snap.keys()
            assert sum(per_index.values()) == snap["devcache.resident_bytes"]
            assert DEVICE_CACHE.bytes_used == snap["devcache.resident_bytes"]

    def test_deleted_index_leaves_the_device_ledger(self, paging_env):
        """View-level stacks (row stacks, tally bundles) are owned by
        the view token: index deletion must drop them from the device
        cache so the dead tenant's label cannot resurrect."""
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            _seed(srv.api, "gonner", n_shards=2)
            srv.api.query("gonner", "Count(Row(f=0))")
            assert DEVICE_CACHE.index_resident_bytes().get("gonner", 0) > 0
            srv.api.delete_index("gonner")
            assert DEVICE_CACHE.index_resident_bytes().get("gonner", 0) == 0

    def test_zombie_pins_cannot_resurrect_a_dropped_label(self, paging_env):
        """Delete an index while a dispatch still pins its extents: the
        invalidated-while-pinned (zombie) bytes stay on the ledger by
        design, but drop_index must re-bucket their attribution to "-"
        so the next gauge publish cannot re-create the dropped per-index
        series — while the per-index sum keeps equaling the ledger."""
        arr = np.zeros(64, np.uint32)
        key = ("zomb", 0)
        DEVICE_CACHE.put(key, arr, index="ztenant")
        assert DEVICE_CACHE.pin_if_present(key)
        DEVICE_CACHE.invalidate(key)  # in-flight: bytes become zombie
        assert DEVICE_CACHE.index_resident_bytes()["ztenant"] == arr.nbytes
        hbm_res.drop_index("ztenant")  # the delete-index GC hook
        by_index = DEVICE_CACHE.index_resident_bytes()
        assert "ztenant" not in by_index
        # sum invariant survives: the zombie bytes report unattributed
        assert by_index.get("-", 0) == arr.nbytes
        assert sum(by_index.values()) == DEVICE_CACHE.bytes_used
        DEVICE_CACHE.unpin(key)  # last unpin releases the zombie bytes
        assert DEVICE_CACHE.bytes_used == 0
        assert "ztenant" not in DEVICE_CACHE.index_resident_bytes()


# ---------------------------------------------------------------------------
# label GC: a churning tenant set cannot leak metric series
# ---------------------------------------------------------------------------


class TestLabelGC:
    def test_create_delete_100_indexes_returns_to_baseline(self):
        # generous tenant limits: the quota machinery runs (per-index
        # gauges, rate buckets, quota ledgers) without ever shedding,
        # so the churn also proves the tenant series and bucket state GC
        with ClusterHarness(
            1,
            in_memory=True,
            tenant_default_qps=1e9,
            tenant_default_hbm_bytes=1 << 30,
            tenant_default_cache_bytes=1 << 30,
        ) as c:
            srv = c[0]

            from pilosa_tpu.core.resultcache import RESULT_CACHE

            def churn(idx):
                _seed(srv.api, idx, n_shards=1, rows=1)
                # query TWICE: the repeat stores+serves a result-cache
                # entry, so the churn also exercises cache.* per-index
                # attribution and its cache.resident_bytes{index} series
                srv.api.query(idx, "Count(Row(f=0))")
                srv.api.query(idx, "Count(Row(f=0))")
                # a live subscription per tenant: the delete must close
                # it and GC its coherence.subscriptions{index} series
                sub = srv.api.subscribe(idx, "Count(Row(f=0))")
                srv.publish_cache_gauges()
                assert RESULT_CACHE.stats_snapshot()["by_index"].get(idx, 0) > 0
                srv.api.delete_index(idx)
                assert srv.coherence.poll(sub["id"], -1, 0.0) is None
                srv.publish_cache_gauges()

            # warm-up round creates every GLOBAL series (sched gauges,
            # devcache gauges, class:interactive,index:- lanes, ...)
            churn("warm0")
            baseline = set(srv.stats.registry.snapshot())
            cache_base = RESULT_CACHE.stats_snapshot()["resident_bytes"]
            for i in range(100):
                churn(f"tenant_{i}")
            final = set(srv.stats.registry.snapshot())
            leaked = {k for k in final - baseline if "tenant_" in k}
            assert leaked == set(), sorted(leaked)[:10]
            assert len(final) == len(baseline), (
                sorted(final - baseline)[:10],
                sorted(baseline - final)[:10],
            )
            # cache bytes return to baseline with no tenant attribution
            csnap = RESULT_CACHE.stats_snapshot()
            assert csnap["resident_bytes"] == cache_base
            assert not any(k.startswith("tenant_") for k in csnap["by_index"])
            # the tenant policy's lazy bucket map is GC'd with the index
            assert srv.tenant_policy.bucket_count() == 0
            assert not any(
                k.startswith("tenant_")
                for k in csnap["quota_evictions_by_index"]
            )
            # every churned subscription is gone from the coherence plane
            assert srv.coherence.list_subscriptions() == []
            assert srv.coherence.gauges() == {"leases": 0, "grants": 0}

    def test_release_after_drop_cannot_resurrect_the_series(self):
        """Delete an index while its query is in flight: the release's
        byte decrement lands after drop_index popped the attribution
        key. Re-inserting it (even at 0) would re-emit the gauge and
        re-create the registry series the label GC just removed."""
        from pilosa_tpu.sched.admission import AdmissionController
        from pilosa_tpu.sched.cost import QueryCost
        from pilosa_tpu.utils.stats import StatsClient

        st = StatsClient()
        ctl = AdmissionController(max_concurrent=2, stats=st)
        t = ctl.admit(cost=QueryCost(device_bytes=64), index="gone")
        assert ctl.inflight_bytes_by_index() == {"gone": 64}
        ctl.drop_index("gone")
        st.registry.drop_label("index", "gone")  # the GC hook's other half
        t.release()
        assert ctl.inflight_bytes_by_index() == {}
        held = [
            k for k in st.registry.snapshot() if "index:gone" in k
        ]
        assert held == [], held

    def test_delete_broadcast_gcs_labels_on_peers(self):
        """The delete-index broadcast must GC per-index series on every
        member, not just the coordinator — including the coherence
        plane: leases revoked, grants dropped, subscriptions closed."""
        with ClusterHarness(
            3, replica_n=1, in_memory=True, coherence_lease_duration=30.0
        ) as c:
            _seed(c[0].api, "bye", n_shards=6)
            for _ in range(2):
                c[0].api.query("bye", "Count(Row(f=0))")
            sub = c[0].api.subscribe("bye", "Count(Row(f=0))")
            # the leased fan-out armed mirrors/grants across the cluster
            assert c[0].coherence.gauges()["leases"] >= 1
            assert any(
                s.coherence.gauges()["grants"] >= 1 for s in c.nodes
            )
            # fan-out legs created per-index series on the peers
            assert any(
                "index:bye" in k
                for s in c.nodes
                for k in s.stats.registry.snapshot()
            )
            c[0].api.delete_index("bye")
            assert c[0].coherence.poll(sub["id"], -1, 0.0) is None
            for s in c.nodes:
                assert s.coherence.gauges() == {"leases": 0, "grants": 0}
                assert s.coherence.list_subscriptions() == []
                s.publish_cache_gauges()
                held = [
                    k
                    for k in s.stats.registry.snapshot()
                    if "index:bye" in k
                ]
                assert held == [], (s.node.id, held)


# ---------------------------------------------------------------------------
# utilization timeline
# ---------------------------------------------------------------------------


class TestTimeline:
    def test_sampler_ring_and_rates(self):
        with ClusterHarness(
            1, in_memory=True, telemetry_ring=3,
            telemetry_sample_interval=0.0,  # tick manually
        ) as c:
            srv = c[0]
            _seed(srv.api, "tl", n_shards=1)
            sampler = srv.telemetry.sampler
            first = sampler.sample_once()
            for key in (
                "hbmResidentBytes",
                "hbmPinnedBytes",
                "queueDepth",
                "inflightBytes",
                "inflightBytesByIndex",
                "ingestBits",
                "ingestBitsPerS",
                "queries",
                "queriesPerS",
                "resizePhase",
                "walStagedPositions",
            ):
                assert key in first, key
            assert first["ingestBits"] > 0  # _seed imported bits
            srv.api.query("tl", "Count(Row(f=0))")
            second = sampler.sample_once()
            assert second["queries"] == first["queries"] + 1
            assert second["queriesPerS"] > 0
            for _ in range(4):
                sampler.sample_once()
            snap = sampler.snapshot()
            assert len(snap["samples"]) == 3  # ring bound holds
            assert snap["node"] == srv.node.id

    def test_background_ticker_fills_the_ring(self):
        """The real [telemetry] sampler thread: samples accumulate with
        no scrape and no manual tick."""
        import time

        with ClusterHarness(
            1, in_memory=True, telemetry_sample_interval=0.02,
        ) as c:
            srv = c[0]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(srv.telemetry.sampler.snapshot()["samples"]) >= 2:
                    break
                time.sleep(0.02)
            assert len(srv.telemetry.sampler.snapshot()["samples"]) >= 2
            # the tick refreshed the gauges scrape-free
            assert "devcache.resident_bytes" in srv.stats.registry.snapshot()

    def test_debug_timeline_http_and_sample_param(self):
        with ClusterHarness(
            1, in_memory=True, telemetry_sample_interval=0.0
        ) as c:
            srv = c[0]
            tl = http_json("GET", f"{srv.node.uri}/debug/timeline")
            assert tl["samples"] == []
            tl = http_json(
                "GET", f"{srv.node.uri}/debug/timeline?sample=1"
            )
            assert len(tl["samples"]) == 1

    def test_sampler_refreshes_gauges_without_scrape(self):
        """Satellite fix: the residency gauges must reach the registry
        (hence any statsd backend) from the sampler tick alone — no
        /metrics scrape anywhere."""
        with ClusterHarness(
            1, in_memory=True, telemetry_sample_interval=0.0
        ) as c:
            srv = c[0]
            _seed(srv.api, "gv", n_shards=1)
            srv.api.query("gv", "Count(Row(f=0))")
            assert "devcache.resident_bytes" not in srv.stats.registry.snapshot()
            srv.telemetry.sampler.sample_once()
            snap = srv.stats.registry.snapshot()
            assert snap["devcache.resident_bytes"] >= 0
            assert "hbm.resident_extents" in snap

    def test_cluster_timeline_groups_by_node(self):
        with ClusterHarness(
            3, replica_n=1, in_memory=True,
            telemetry_sample_interval=0.0,
        ) as c:
            for s in c.nodes:
                s.telemetry.sampler.sample_once()
            merged = http_json(
                "GET", f"{c[0].node.uri}/cluster/timeline"
            )
            assert set(merged["nodes"]) == {"node0", "node1", "node2"}
            for nid, row in merged["nodes"].items():
                assert row["stale"] is False
                assert len(row["samples"]) == 1


# ---------------------------------------------------------------------------
# /cluster/health
# ---------------------------------------------------------------------------


class TestClusterHealth:
    def test_healthy_cluster_reports_ok(self):
        with ClusterHarness(3, replica_n=1, in_memory=True) as c:
            h = http_json("GET", f"{c[0].node.uri}/cluster/health")
            assert h["status"] == "ok"
            assert h["reasons"] == []
            assert len(h["nodes"]) == 3
            assert all(n["reachable"] for n in h["nodes"])
            # /status links the verdict
            st = http_json("GET", f"{c[0].node.uri}/status")
            assert st["health"] == "/cluster/health"
            assert "walStagedPositions" in st

    def test_down_replica_degrades(self):
        with ClusterHarness(3, replica_n=2, in_memory=True) as c:
            c.stop_node(2)
            h = http_json("GET", f"{c[0].node.uri}/cluster/health")
            assert h["status"] == "degraded"
            assert any("node2 unreachable" in r for r in h["reasons"])
            row = [n for n in h["nodes"] if n["id"] == "node2"][0]
            assert row["reachable"] is False

    def test_unreachable_at_replica_n_is_critical(self):
        with ClusterHarness(3, replica_n=1, in_memory=True) as c:
            c.stop_node(1)
            h = http_json("GET", f"{c[0].node.uri}/cluster/health")
            assert h["status"] == "critical"
            assert any("no live owner" in r for r in h["reasons"])

    def test_pending_repairs_surface(self):
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            srv.holder.record_pending_repair("idx", 0, "ghost")
            h = http_json("GET", f"{srv.node.uri}/cluster/health")
            assert h["status"] == "degraded"
            assert h["pendingRepairs"] == 1
            assert any("pending replica repair" in r for r in h["reasons"])


# ---------------------------------------------------------------------------
# acceptance: 3-node federated rollup
# ---------------------------------------------------------------------------


def _hist_for(state, name, index):
    """One node's exported query_ms histogram dict for an index tag."""
    for n, t, d in state.get("hists", ()):
        if n == name and f"index:{index}" in t:
            return d
    return None


def _cluster_bucket_counts(text, index):
    """[(le, cum)] + count for query_ms{index=...} from exposition."""
    buckets, count = [], None
    for line in text.splitlines():
        if line.startswith("pilosa_tpu_query_ms_bucket") and (
            f'index="{index}"' in line
        ):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            bound = float("inf") if le == "+Inf" else float(le)
            buckets.append((bound, float(line.rsplit(" ", 1)[1])))
        elif line.startswith("pilosa_tpu_query_ms_count") and (
            f'index="{index}"' in line
        ):
            count = float(line.rsplit(" ", 1)[1])
    return buckets, count


def test_three_node_rollup_acceptance():
    """ISSUE 8 acceptance: (a) /cluster/metrics per-index query_ms
    counts equal the sum of the three per-node counts exactly; (b) the
    cluster p99 derives from merged buckets — one seeded-slow node
    pulls it up even though the other two nodes' p99s are fast; (c)
    killing one node degrades /cluster/overview to stale-marked data
    for that peer without failing the endpoint."""
    with ClusterHarness(3, replica_n=1, in_memory=True) as c:
        uri = c[0].node.uri
        _seed(c[0].api, "ten_a", n_shards=6)
        _seed(c[0].api, "ten_b", n_shards=6)
        for _ in range(4):
            http_json(
                "POST", f"{uri}/index/ten_a/query",
                {"query": "Count(Row(f=0))"},
            )
        for _ in range(2):
            http_json(
                "POST", f"{uri}/index/ten_b/query",
                {"query": "Count(Row(f=1))"},
            )
        # seeded skew: node2 observed slow ten_a queries (5 s each);
        # enough of them that the true cluster p99 sits in the slow
        # regime while node0/node1 report fast p99s
        for _ in range(3):
            c[2].stats.with_tags("index:ten_a").timing("query_ms", 5.0)

        # (a) exact per-index counter merge: cluster == sum of nodes
        node_states = [
            http_json("GET", f"{s.node.uri}/internal/stats")["stats"]
            for s in c.nodes
        ]
        per_node = [
            _hist_for(st, "query_ms", "ten_a") for st in node_states
        ]
        want_count = sum(int(d["count"]) for d in per_node if d)
        want_sum = sum(float(d["sum"]) for d in per_node if d)
        assert want_count >= 4 + 3  # coordinator + seeded observations

        with urllib.request.urlopen(
            f"{uri}/cluster/metrics", timeout=30
        ) as r:
            text = r.read().decode()
        buckets, count = _cluster_bucket_counts(text, "ten_a")
        assert count == want_count  # EXACT, not approximate
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == count
        m = [
            ln
            for ln in text.splitlines()
            if ln.startswith("pilosa_tpu_query_ms_sum")
            and 'index="ten_a"' in ln
        ]
        assert float(m[0].rsplit(" ", 1)[1]) == pytest.approx(
            want_sum, rel=1e-9
        )
        # ten_b series exist and are disjoint from ten_a's
        _, count_b = _cluster_bucket_counts(text, "ten_b")
        assert count_b and count_b < count

        # (b) merged-bucket p99: the slow node dominates the tail
        overview = http_json("GET", f"{uri}/cluster/overview")
        ten_a = overview["indexes"]["ten_a"]
        assert ten_a["queryMsP99"] > 1000  # seeded 5 s observations
        assert ten_a["queryMsP50"] < ten_a["queryMsP99"]
        # the two fast nodes' own p99s do NOT show the tail
        for s in (c[0], c[1]):
            fast = s.stats.registry.quantile(
                "query_ms", 0.99, ("index:ten_a",)
            )
            assert fast < 1000, (s.node.id, fast)
        assert overview["totals"]["queries"] > 0
        assert {n["id"] for n in overview["nodes"]} == {
            "node0", "node1", "node2",
        }
        assert not any(n["stale"] for n in overview["nodes"])

        # (c) kill node2: the rollup degrades, never 500s
        c.stop_node(2)
        degraded = http_json("GET", f"{uri}/cluster/overview")
        rows = {n["id"]: n for n in degraded["nodes"]}
        assert rows["node2"]["stale"] is True
        assert rows["node2"]["ageS"] is not None
        assert rows["node0"]["stale"] is False
        # the cached snapshot keeps contributing: ten_a's seeded tail
        # survives in the merged quantile
        assert degraded["indexes"]["ten_a"]["queryMsP99"] > 1000
        with urllib.request.urlopen(
            f"{uri}/cluster/metrics", timeout=30
        ) as r:
            text2 = r.read().decode()
        assert 'pilosa_tpu_cluster_peer_stale{node="node2"} 1' in text2
        assert 'pilosa_tpu_cluster_peer_stale{node="node0"} 0' in text2
        # health sees it too (replica_n=1 -> critical)
        h = http_json("GET", f"{uri}/cluster/health")
        assert h["status"] == "critical"


def test_malformed_peer_body_degrades_stale_not_500(monkeypatch):
    """A peer answering 200 with a non-JSON body (mid-restart, error
    page from a proxy in front of it) must degrade exactly like a dead
    peer — the rollup endpoints promise staleness markers, never a
    500."""
    import json as _json

    with ClusterHarness(2, replica_n=1, in_memory=True) as c:
        srv = c[0]

        def garbled(uri, timeout=5.0):
            raise _json.JSONDecodeError("Expecting value", "<html>", 0)

        monkeypatch.setattr(srv.client, "node_stats", garbled)
        monkeypatch.setattr(srv.client, "node_timeline", garbled)
        ov = http_json("GET", f"{srv.node.uri}/cluster/overview")
        rows = {n["id"]: n for n in ov["nodes"]}
        assert rows["node1"]["stale"] is True
        assert rows["node0"]["stale"] is False
        tl = http_json("GET", f"{srv.node.uri}/cluster/timeline")
        assert tl["nodes"]["node1"]["stale"] is True

        # valid JSON of the WRONG SHAPE (proxy maintenance page) must
        # degrade the same way, not AttributeError into a 500
        def listy(uri, timeout=5.0):
            return ["maintenance"]

        monkeypatch.setattr(srv.client, "node_stats", listy)
        monkeypatch.setattr(srv.client, "node_timeline", listy)
        ov = http_json("GET", f"{srv.node.uri}/cluster/overview")
        assert {n["id"]: n["stale"] for n in ov["nodes"]}["node1"] is True
        tl = http_json("GET", f"{srv.node.uri}/cluster/timeline")
        assert tl["nodes"]["node1"]["stale"] is True


def test_internal_stats_export_is_mergeable_shape():
    with ClusterHarness(1, in_memory=True) as c:
        srv = c[0]
        _seed(srv.api, "ms", n_shards=1)
        srv.api.query("ms", "Count(Row(f=0))")
        payload = http_json("GET", f"{srv.node.uri}/internal/stats")
        assert payload["node"] == srv.node.id
        st = payload["stats"]
        assert st["histBuckets"] == len(HIST_BOUNDS) + 1
        merged = Registry()
        merged.merge_state(st)
        assert merged.quantile("query_ms", 0.5, ("index:ms",)) >= 0
        assert math.isfinite(payload["collectedAt"])
