"""Plane-streamed BSI aggregates (ISSUE 15 tentpole): randomized
differential harness against a host value model across all three
execution paths, slab/budget chunking equivalence, dispatch-count
contracts, the batched extent-patch cascade, and the knob plumbing.

The oracle is a plain python dict {column: value} maintained alongside
every mutation — Sum/Min/Max/Range answers are recomputed from it with
numpy and must match bit-for-bit whatever the slab size, budget chunking
or execution path.
"""

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DEVICE_CACHE
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec import bsistream
from pilosa_tpu.exec import plan as planmod
from pilosa_tpu.hbm import residency as hbm_res
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.pql import parse
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW
from pilosa_tpu.testing import ClusterHarness


@pytest.fixture
def stream_env():
    """Single-device staging, default slab knob, restored budget —
    the deterministic environment the dispatch-count asserts need."""
    old_mesh = pmesh.active_mesh()
    pmesh.set_active_mesh(None)
    old_budget = DEVICE_CACHE.budget_bytes
    old_slab = bsistream.slab_planes()
    DEVICE_CACHE.clear()
    bsistream.reset_stats()
    yield
    bsistream.configure(slab_planes=old_slab)
    DEVICE_CACHE.budget_bytes = old_budget
    DEVICE_CACHE.clear()
    bsistream.reset_stats()
    pmesh.set_active_mesh(old_mesh)


# ---------------------------------------------------------------------------
# the host oracle
# ---------------------------------------------------------------------------


def _expected(model: dict, pql_kind: str, arg=None):
    vals = np.array(list(model.values()), np.int64)
    if pql_kind == "sum":
        return (int(vals.sum()), len(vals)) if len(vals) else (0, 0)
    if pql_kind == "min":
        if not len(vals):
            return (0, 0)
        return (int(vals.min()), int((vals == vals.min()).sum()))
    if pql_kind == "max":
        if not len(vals):
            return (0, 0)
        return (int(vals.max()), int((vals == vals.max()).sum()))
    if pql_kind == "between":
        lo, hi = arg
        return int(((vals >= lo) & (vals <= hi)).sum()) if len(vals) else 0
    op, pred = arg
    if not len(vals):
        return 0
    return int(
        {
            ">": vals > pred, ">=": vals >= pred,
            "<": vals < pred, "<=": vals <= pred,
            "==": vals == pred, "!=": vals != pred,
        }[op].sum()
    )


def _check_all(run, model: dict, fname: str, fmin: int, fmax: int, rng):
    """Assert every aggregate family against the oracle through `run`
    (a callable pql -> first result). Predicates cover in-range,
    boundary, zero-crossing and saturated (out-of-range) values."""
    want_v, want_c = _expected(model, "sum")
    vc = run(f"Sum(field={fname})")
    assert (vc.value, vc.count) == (want_v, want_c), ("sum", vc)
    want_v, want_c = _expected(model, "min")
    vc = run(f"Min(field={fname})")
    assert (vc.value, vc.count) == (want_v, want_c), ("min", vc)
    want_v, want_c = _expected(model, "max")
    vc = run(f"Max(field={fname})")
    assert (vc.value, vc.count) == (want_v, want_c), ("max", vc)
    mid = (fmin + fmax) // 2
    some = next(iter(model.values())) if model else mid
    preds = [
        mid, fmin, fmax, 0, some,
        fmin - 7, fmax + 7,  # saturated both sides
        int(rng.integers(fmin, fmax + 1)),
    ]
    for op in (">", ">=", "<", "<=", "==", "!="):
        for pred in preds:
            got = run(f"Count(Row({fname} {op} {pred}))")
            want = _expected(model, "range", (op, pred))
            assert got == want, (op, pred, got, want)
    for lo, hi in [
        (fmin, fmax), (mid, fmax + 9), (fmin - 9, mid), (some, some),
        tuple(sorted(rng.integers(fmin, fmax + 1, 2).tolist())),
    ]:
        got = run(f"Count(Row({fname} >< [{lo},{hi}]))")
        assert got == _expected(model, "between", (lo, hi)), (lo, hi, got)
    got = run(f"Count(Row({fname} != null))")
    assert got == len(model), ("notnull", got, len(model))


def _populate(idx, fname: str, fmin: int, fmax: int, n: int, n_shards: int,
              rng, seed_field=None):
    f = idx.create_field(fname, FieldOptions(type="int", min=fmin, max=fmax))
    cols = rng.choice(
        n_shards * SHARD_WIDTH, size=n, replace=False
    ).astype(np.uint64)
    vals = rng.integers(fmin, fmax + 1, n).astype(np.int64)
    # boundary values are always present (sign/saturation edges)
    vals[0], vals[1] = fmin, fmax
    f.import_values(cols, vals)
    return f, dict(zip(cols.tolist(), vals.tolist()))


# ---------------------------------------------------------------------------
# single-node differential harness
# ---------------------------------------------------------------------------


class TestSingleNodeDifferential:
    @pytest.mark.parametrize(
        "fmin,fmax",
        [
            (0, 255),  # unsigned, base 0
            (-300, 300),  # signed around zero
            (1000, 66_000),  # positive base offset (base = min)
            (-9000, -100),  # all-negative (base = max)
        ],
    )
    def test_families_vs_oracle(self, stream_env, fmin, fmax):
        rng = np.random.default_rng(17)
        h = Holder().open()
        idx = h.create_index("bs")
        _f, model = _populate(idx, "v", fmin, fmax, 500, 5, rng)
        ex = Executor(h)
        _check_all(
            lambda q: ex.execute("bs", q)[0], model, "v", fmin, fmax, rng
        )

    def test_randomized_mutation_interleavings(self, stream_env):
        """set_value / import_values / clear_value interleaved with the
        aggregate families — staged-merge interplay and value
        overwrites must keep the streamed answers exact."""
        rng = np.random.default_rng(23)
        fmin, fmax = -500, 1500
        h = Holder().open()
        idx = h.create_index("bs")
        f, model = _populate(idx, "v", fmin, fmax, 300, 4, rng)
        ex = Executor(h)
        run = lambda q: ex.execute("bs", q)[0]  # noqa: E731
        for _round in range(4):
            op = rng.integers(0, 3)
            if op == 0:  # bulk overwrite/extend
                cols = rng.integers(
                    0, 4 * SHARD_WIDTH, 120
                ).astype(np.uint64)
                vals = rng.integers(fmin, fmax + 1, 120).astype(np.int64)
                f.import_values(cols, vals)
                model.update(zip(cols.tolist(), vals.tolist()))
            elif op == 1:  # point writes
                for _ in range(10):
                    col = int(rng.integers(0, 4 * SHARD_WIDTH))
                    val = int(rng.integers(fmin, fmax + 1))
                    f.set_value(col, val)
                    model[col] = val
            else:  # clears of existing columns
                for col in list(model)[:10]:
                    f.clear_value(col)
                    del model[col]
            _check_all(run, model, "v", fmin, fmax, rng)

    def test_filtered_aggregates(self, stream_env):
        rng = np.random.default_rng(5)
        h = Holder().open()
        idx = h.create_index("bs")
        _f, model = _populate(idx, "v", -100, 900, 400, 3, rng)
        rf = idx.create_field("r", FieldOptions())
        half = np.array(list(model)[: len(model) // 2], np.uint64)
        rf.import_bits(np.zeros(len(half), np.uint64), half)
        ex = Executor(h)
        sel = np.array([model[c] for c in half.tolist()], np.int64)
        (vc,) = ex.execute("bs", "Sum(Row(r=0), field=v)")
        assert (vc.value, vc.count) == (int(sel.sum()), len(sel))
        (vc,) = ex.execute("bs", "Min(Row(r=0), field=v)")
        assert vc.value == int(sel.min())
        assert vc.count == int((sel == sel.min()).sum())
        (vc,) = ex.execute("bs", "Max(Row(r=0), field=v)")
        assert vc.value == int(sel.max())
        assert vc.count == int((sel == sel.max()).sum())
        # filter matching nothing
        (vc,) = ex.execute("bs", "Sum(Row(r=7), field=v)")
        assert (vc.value, vc.count) == (0, 0)

    @pytest.mark.parametrize("extent_rows", [1, 2, 3, 0])
    def test_extent_parts_equivalence(self, stream_env, extent_rows):
        """The kernels consume the extents as PART tuples with no
        device-side concat — answers must be identical whatever the
        paging granularity (multi-part, uneven tail part, monolithic),
        and a warm filterless aggregate stays ONE dispatch however many
        parts the operands split into."""
        old_rows = hbm_res.extent_rows()
        try:
            hbm_res.configure(extent_rows=extent_rows)
            rng = np.random.default_rng(53)
            h = Holder().open()
            idx = h.create_index("bs")
            _f, model = _populate(idx, "v", -200, 600, 300, 7, rng)
            ex = Executor(h)
            _check_all(
                lambda q: ex.execute("bs", q)[0], model, "v", -200, 600,
                rng,
            )
            ex.execute("bs", "Sum(field=v)")  # warm
            ev0, rd0 = planmod.STATS["evals"], planmod.STATS["host_reads"]
            ex.execute("bs", "Sum(field=v)")
            assert planmod.STATS["evals"] - ev0 == 1
            assert planmod.STATS["host_reads"] - rd0 == 1
        finally:
            hbm_res.configure(extent_rows=old_rows)

    def test_multi_slab_carried_state(self, stream_env):
        """A field deeper than the slab knob walks MSB-first slabs with
        carried ladder state — answers must be bit-identical to the
        single-slab lowering, for every family."""
        rng = np.random.default_rng(31)
        fmin, fmax = -40_000, 700_000  # bit_depth ~20
        h = Holder().open()
        idx = h.create_index("bs")
        _f, model = _populate(idx, "v", fmin, fmax, 400, 3, rng)
        ex = Executor(h)
        run = lambda q: ex.execute("bs", q)[0]  # noqa: E731
        bsistream.configure(slab_planes=64)  # force single slab
        DEVICE_CACHE.clear()
        _check_all(run, model, "v", fmin, fmax, rng)
        for slab in (7, 3, 1):
            bsistream.configure(slab_planes=slab)
            DEVICE_CACHE.clear()
            _check_all(run, model, "v", fmin, fmax, rng)

    def test_budget_chunk_boundaries(self, stream_env):
        """Values straddling budget-chunk boundaries: a quarter-budget
        too small for one slab over every shard forces BudgetExceeded
        halving — per-chunk partials must combine to the same answers,
        and each chunk pays exactly one dispatch (counter-asserted for
        the filterless single-slab families)."""
        rng = np.random.default_rng(41)
        fmin, fmax = -10, 12  # depth 4: slab covers it
        n_shards = 32
        h = Holder().open()
        idx = h.create_index("bs")
        f = idx.create_field(
            "v", FieldOptions(type="int", min=fmin, max=fmax)
        )
        # every shard populated, extremes placed in FIRST and LAST
        # chunks so the cross-chunk combine is exercised
        cols, vals = [], []
        for s in range(n_shards):
            c = (s * SHARD_WIDTH + rng.choice(
                SHARD_WIDTH, 40, replace=False
            )).astype(np.uint64)
            v = rng.integers(fmin + 1, fmax, 40).astype(np.int64)
            cols.append(c)
            vals.append(v)
        vals[0][0] = fmin
        vals[-1][0] = fmax
        cols_a = np.concatenate(cols)
        vals_a = np.concatenate(vals)
        f.import_values(cols_a, vals_a)
        model = dict(zip(cols_a.tolist(), vals_a.tolist()))
        ex = Executor(h)
        run = lambda q: ex.execute("bs", q)[0]  # noqa: E731
        _check_all(run, model, "v", fmin, fmax, rng)  # unchunked truth
        # quarter-budget fits a 16-shard chunk but not all 32
        stack = WORDS_PER_ROW * 4
        mult = min(4, bsistream.slab_planes()) + 3
        DEVICE_CACHE.budget_bytes = 4 * (20 * stack * mult)
        DEVICE_CACHE.clear()
        _check_all(run, model, "v", fmin, fmax, rng)
        # dispatch shape: 2 chunks -> exactly 2 dispatches + 2 reads
        for q in ("Sum(field=v)", "Min(field=v)", "Count(Row(v > 3))"):
            ex.execute("bs", q)  # warm (plus result-cache decoupling)
            ev0, rd0 = planmod.STATS["evals"], planmod.STATS["host_reads"]
            from pilosa_tpu.core.resultcache import RESULT_CACHE

            RESULT_CACHE.reset()  # the Count repeat must re-execute
            ex.execute("bs", q)
            assert planmod.STATS["evals"] - ev0 == 2, q
            assert planmod.STATS["host_reads"] - rd0 == 2, q

    def test_one_dispatch_one_read_at_depth_under_slab(self, stream_env):
        """The roofline contract: a warm filterless aggregate on a field
        at or under the slab is exactly ONE compiled dispatch + ONE
        scalar host read, whatever the shard count."""
        rng = np.random.default_rng(43)
        h = Holder().open()
        idx = h.create_index("bs")
        _f, model = _populate(idx, "v", -100, 100, 300, 6, rng)
        ex = Executor(h)
        for q in ("Sum(field=v)", "Min(field=v)", "Max(field=v)"):
            ex.execute("bs", q)  # warm: stage + compile
            ev0, rd0 = planmod.STATS["evals"], planmod.STATS["host_reads"]
            sl0 = bsistream.stats_snapshot()["plane_dispatches"]
            (vc,) = ex.execute("bs", q)
            kind = q[:3].lower()
            want_v, want_c = _expected(model, kind)
            assert (vc.value, vc.count) == (want_v, want_c), q
            assert planmod.STATS["evals"] - ev0 == 1, q
            assert planmod.STATS["host_reads"] - rd0 == 1, q
            assert bsistream.stats_snapshot()["plane_dispatches"] - sl0 == 1
        # Range counts: traced predicates — changing the threshold reuses
        # the compiled program AND dodges the result cache's text key
        ex.execute("bs", "Count(Row(v > 17))")  # warm the program
        ev0, rd0 = planmod.STATS["evals"], planmod.STATS["host_reads"]
        got = ex.execute("bs", "Count(Row(v > 18))")[0]
        assert got == _expected(model, "range", (">", 18))
        assert planmod.STATS["evals"] - ev0 == 1
        assert planmod.STATS["host_reads"] - rd0 == 1

    def test_depth32_and_unstreamable_fall_back(self, stream_env):
        """bit_depth 32 (the uint32 key-width edge) declines the
        streamed path but must still answer exactly via the legacy
        lowering."""
        rng = np.random.default_rng(47)
        h = Holder().open()
        idx = h.create_index("bs")
        f = idx.create_field(
            "v", FieldOptions(type="int", min=0, max=(1 << 32) - 1)
        )
        assert f.options.bit_depth == 32
        cols = rng.choice(2 * SHARD_WIDTH, 50, replace=False).astype(np.uint64)
        vals = rng.integers(0, 1 << 32, 50).astype(np.int64)
        vals[0], vals[1] = 0, (1 << 32) - 1
        f.import_values(cols, vals)
        model = dict(zip(cols.tolist(), vals.tolist()))
        ex = Executor(h)
        mv = np.array(list(model.values()))
        (vc,) = ex.execute("bs", "Sum(field=v)")
        assert (vc.value, vc.count) == (int(mv.sum()), len(mv))
        (vc,) = ex.execute("bs", "Min(field=v)")
        assert vc.value == int(mv.min())
        (vc,) = ex.execute("bs", "Max(field=v)")
        assert vc.value == int(mv.max())


# ---------------------------------------------------------------------------
# decomposition units
# ---------------------------------------------------------------------------


class TestDecompose:
    def _field(self, fmin, fmax):
        h = Holder().open()
        idx = h.create_index("d")
        return idx.create_field(
            "v", FieldOptions(type="int", min=fmin, max=fmax)
        )

    def _cond(self, pql):
        return next(iter(parse(pql).calls[0].condition_args().values()))

    def test_unsigned_collapse(self):
        f = self._field(0, 100)
        jobs, preds, w, extras = bsistream._decompose(
            f, self._cond("Row(v < 50)"), False
        )
        # positives collapse to consider; the negatives extra drops
        assert jobs == (("lt", "consider", False),)
        assert preds == (50,) and w == (1,) and extras == ()

    def test_signed_keeps_branches(self):
        f = self._field(-100, 100)
        jobs, preds, w, extras = bsistream._decompose(
            f, self._cond("Row(v < 50)"), True
        )
        assert jobs == (("lt", "pos", False),)
        assert extras == (("neg", 1),)

    def test_neq_is_subtractive(self):
        f = self._field(-100, 100)
        jobs, _preds, w, extras = bsistream._decompose(
            f, self._cond("Row(v != 7)"), True
        )
        assert jobs == (("eq", "pos", False),)
        assert w == (-1,) and extras == (("consider", 1),)

    def test_saturated_is_zero_or_all(self):
        f = self._field(0, 100)
        assert bsistream._decompose(
            f, self._cond("Row(v > 5000)"), False
        ) == bsistream._ZERO
        dec = bsistream._decompose(f, self._cond("Row(v < 5000)"), False)
        assert dec == ((), (), (), (("consider", 1),))

    def test_between_straddle(self):
        f = self._field(-100, 100)
        jobs, preds, w, extras = bsistream._decompose(
            f, self._cond("Row(v >< [-10,20])"), True
        )
        assert jobs == (("lt", "pos", True), ("lt", "neg", True))
        assert preds == (20, 10) and w == (1, 1)


# ---------------------------------------------------------------------------
# batched extent-patch cascade (satellite)
# ---------------------------------------------------------------------------


class TestPatchCascadeBatching:
    def test_smeared_burst_is_one_scatter_per_entry(self, stream_env):
        """A staged burst smeared over EVERY shard of a warm operand is
        patched with one gather|OR|scatter per resident entry — not one
        full-extent copy per dirty shard (the 11.6 s round-10 cliff)."""
        hbm_res.configure(extent_rows=8)  # 32 shards -> 4 extents
        hbm_res.reset_stats()
        DEVICE_CACHE.budget_bytes = 1 << 30
        S = 32
        rng = np.random.default_rng(3)
        h = Holder().open()
        idx = h.create_index("pb")
        f = idx.create_field("f", FieldOptions())
        for s in range(S):
            f.import_row_words(
                0, s, rng.integers(0, 2**32, WORDS_PER_ROW).astype(np.uint32)
            )
        ex = Executor(h)
        q = "Count(Row(f=0))"
        got1 = ex.execute("pb", q)[0]  # warm: 4 extents resident
        # keep the burst STAGED (no op-count snapshot trigger)
        for fr in f.view("standard").fragments.values():
            fr.max_op_n = 1 << 22
        snap1 = hbm_res.stats_snapshot()
        # one row-0 bit into every shard: 32 dirty shards, 4 extents
        cols = np.array(
            [s * SHARD_WIDTH + 77 for s in range(S)], np.uint64
        )
        f.import_bits(np.zeros(S, np.uint64), cols)
        got2 = ex.execute("pb", q)[0]
        snap2 = hbm_res.stats_snapshot()
        assert (
            snap2["extent_patches"] - snap1["extent_patches"] == 4
        ), snap2
        # THE batching property: one scatter per entry, not per shard
        assert (
            snap2["extent_patch_batches"] - snap1["extent_patch_batches"]
            == 4
        ), snap2
        assert snap2["restage_bytes"] == snap1["restage_bytes"]
        # exactness vs a cold re-stage
        DEVICE_CACHE.clear()
        assert ex.execute("pb", q)[0] == got2
        assert got2 >= got1

    def test_plane_stack_patch_batches(self, stream_env):
        """BSI plane stacks patch through the same batched scatter (the
        [D, S, W] index-pair form)."""
        hbm_res.configure(extent_rows=0)  # monolithic: 1 entry per stack
        hbm_res.reset_stats()
        DEVICE_CACHE.budget_bytes = 1 << 30
        rng = np.random.default_rng(9)
        h = Holder().open()
        idx = h.create_index("pb")
        f = idx.create_field("v", FieldOptions(type="int", min=0, max=255))
        S = 6
        cols = rng.choice(S * SHARD_WIDTH, 200, replace=False).astype(np.uint64)
        vals = rng.integers(0, 256, 200).astype(np.int64)
        f.import_values(cols, vals)
        model = dict(zip(cols.tolist(), vals.tolist()))
        ex = Executor(h)
        (vc,) = ex.execute("pb", "Sum(field=v)")  # warm plane stacks
        mv = np.array(list(model.values()))
        assert (vc.value, vc.count) == (int(mv.sum()), len(mv))
        snap1 = hbm_res.stats_snapshot()
        # a set-only burst into existing planes across several shards:
        # row-word bits on plane 0 (odd values gain nothing new — use
        # fresh columns so plane/exists rows genuinely change)
        fresh = np.setdiff1d(
            np.arange(0, S * SHARD_WIDTH, 997, dtype=np.uint64), cols
        )[:60]
        fvals = rng.integers(0, 256, len(fresh)).astype(np.int64)
        bsiv = f.view(f.bsi_view_name())
        for fr in bsiv.fragments.values():
            fr.max_op_n = 1 << 22
        f.import_values(fresh, fvals)
        model.update(zip(fresh.tolist(), fvals.tolist()))
        (vc,) = ex.execute("pb", "Sum(field=v)")
        mv = np.array(list(model.values()))
        assert (vc.value, vc.count) == (int(mv.sum()), len(mv))
        snap2 = hbm_res.stats_snapshot()
        patches = snap2["extent_patches"] - snap1["extent_patches"]
        batches = (
            snap2["extent_patch_batches"] - snap1["extent_patch_batches"]
        )
        if patches:  # import_values may restage instead when unpatchable
            assert batches == patches


# ---------------------------------------------------------------------------
# candidate-window satellite + cost repricing + knob plumbing
# ---------------------------------------------------------------------------


class TestSatellites:
    def test_candidate_window_tracks_budget(self, stream_env):
        row = WORDS_PER_ROW * 4
        DEVICE_CACHE.budget_bytes = 4 * 64 * row  # quarter = 64 rows @ 1 shard
        assert Executor._candidate_window(1) == 64
        assert Executor._candidate_window(8) == 16  # floor
        DEVICE_CACHE.budget_bytes = 1 << 40
        assert Executor._candidate_window(1) == 4096  # ceiling

    def test_cost_prices_slab_peak(self, stream_env):
        from pilosa_tpu.sched.cost import estimate

        h = Holder().open()
        idx = h.create_index("cx")
        idx.create_field(
            "deep", FieldOptions(type="int", min=0, max=(1 << 30) - 1)
        )
        idx.create_field("f", FieldOptions())
        f = idx.field("f")
        f.set_bit(1, 1)
        slab = bsistream.slab_planes()
        stack = WORDS_PER_ROW * 4
        got = estimate(idx, parse("Count(Row(deep > 7))"), shards=[0])
        # slab peak, NOT bit_depth+2 whole-stack (30 planes deep)
        assert got.device_bytes == (min(30, slab) + 3) * stack
        assert got.device_bytes < (30 + 2) * stack

    def test_knob_plumbing_three_way(self):
        from pilosa_tpu.cli.config import Config
        from pilosa_tpu.cli.main import _build_parser

        cfg = Config.load(overrides={"bsi": {"slab_planes": 5}})
        assert cfg.bsi.slab_planes == 5
        assert "slab-planes = 5" in cfg.to_toml()
        args = _build_parser().parse_args(
            ["server", "--bsi-slab-planes", "9"]
        )
        assert args.bsi_slab_planes == 9
        old = bsistream.slab_planes()
        try:
            from pilosa_tpu.server.node import NodeServer

            srv = NodeServer(None, "bsknob", bsi_slab_planes=6)
            srv.start()
            try:
                assert bsistream.slab_planes() == 6
            finally:
                srv.stop()
        finally:
            bsistream.configure(slab_planes=old)

    def test_env_knob(self, monkeypatch):
        from pilosa_tpu.cli.config import Config

        cfg = Config.load(env={"PILOSA_TPU_BSI__SLAB_PLANES": "11"})
        assert cfg.bsi.slab_planes == 11
        # non-positive / garbage env values restore the default instead
        # of making every plane range empty (silently-zero aggregates)
        for raw in ("-4", "0", "nope"):
            monkeypatch.setenv("PILOSA_TPU_BSI_SLAB_PLANES", raw)
            assert bsistream._env_slab_planes() == 16, raw

    def test_configure_rejects_nonpositive(self):
        old = bsistream.slab_planes()
        try:
            bsistream.configure(slab_planes=-3)
            assert bsistream.slab_planes() == 16
            bsistream.configure(slab_planes=5)
            assert bsistream.slab_planes() == 5
        finally:
            bsistream.configure(slab_planes=old)

    def test_cost_prices_legacy_for_streamed_ineligible(self, stream_env):
        """A signed depth-32 field falls back to the legacy whole-stack
        lowering — admission must price the full bit_depth+2 stack, not
        the slab peak (a ~2x under-charge against the byte budget)."""
        from pilosa_tpu.sched.cost import estimate

        h = Holder().open()
        idx = h.create_index("cx2")
        idx.create_field(
            "wide",
            FieldOptions(type="int", min=-1, max=2**32 - 1),
        )
        assert idx.field("wide").options.bit_depth == 32
        stack = WORDS_PER_ROW * 4
        got = estimate(idx, parse("Count(Row(wide > 7))"), shards=[0])
        assert got.device_bytes == (32 + 2) * stack


# ---------------------------------------------------------------------------
# HTTP fan-out + mesh-group differential equivalence
# ---------------------------------------------------------------------------

N_SHARDS = 9


@pytest.fixture(scope="module")
def bsi_cluster():
    with ClusterHarness(
        3, in_memory=True, mesh_group="bsi-ici",
        telemetry_sample_interval=0.0,
    ) as cluster:
        api = cluster[0].api
        api.create_index("bx")
        api.create_field(
            "bx", "v", options={"type": "int", "min": -800, "max": 800}
        )
        api.create_field(
            "bx", "u", options={"type": "int", "min": 100, "max": 4000}
        )
        api.create_field("bx", "f")
        rng = np.random.default_rng(29)
        models = {}
        for fname, fmin, fmax in (("v", -800, 800), ("u", 100, 4000)):
            cols = rng.choice(
                N_SHARDS * SHARD_WIDTH, 3000, replace=False
            ).astype(np.uint64)
            vals = rng.integers(fmin, fmax + 1, 3000).astype(np.int64)
            vals[0], vals[1] = fmin, fmax
            api.import_values("bx", fname, cols, vals)
            models[fname] = dict(zip(cols.tolist(), vals.tolist()))
        fcols = np.array(list(models["v"])[:1500], np.uint64)
        api.import_bits(
            "bx", "f", np.zeros(len(fcols), np.uint64), fcols
        )
        yield cluster, models, fcols


def _set_mesh(cluster, on: bool) -> None:
    for node in cluster.nodes:
        node.executor.mesh_min_nodes = 2 if on else 0


def _both(cluster, pql):
    from pilosa_tpu.exec import meshgroup

    api = cluster[0].api
    _set_mesh(cluster, True)
    meshgroup.reset_stats()
    r_mesh = api.query("bx", pql)
    snap = meshgroup.stats_snapshot()
    _set_mesh(cluster, False)
    try:
        r_http = api.query("bx", pql)
    finally:
        _set_mesh(cluster, True)
    return r_mesh, r_http, snap


class TestClusterDifferential:
    @pytest.mark.parametrize("fname,fmin,fmax", [
        ("v", -800, 800), ("u", 100, 4000),
    ])
    def test_aggregates_all_paths(self, bsi_cluster, fname, fmin, fmax):
        cluster, models, _ = bsi_cluster
        model = models[fname]
        rng = np.random.default_rng(2)

        def run_mesh(q):
            (rm,), (rh,), snap = _both(cluster, q)
            # mesh partial == http partial == oracle, zero fallbacks
            assert snap["fallbacks"] == 0, (q, snap)
            assert snap["dispatches"] >= 1, (q, snap)
            if hasattr(rm, "value"):
                assert (rm.value, rm.count) == (rh.value, rh.count), q
            else:
                assert rm == rh, q
            return rm

        _check_all(run_mesh, model, fname, fmin, fmax, rng)

    def test_mesh_aggregate_one_dispatch_one_read(self, bsi_cluster):
        """The mesh-group contract extended to BSI aggregates: ONE
        compiled dispatch + ONE scalar-sized host read for the whole
        group, regardless of group size."""
        cluster, models, _ = bsi_cluster
        api = cluster[0].api
        _set_mesh(cluster, True)
        for q in ("Sum(field=u)", "Min(field=u)", "Max(field=u)"):
            api.query("bx", q)  # warm: stage + compile
            ev0, rd0 = planmod.STATS["evals"], planmod.STATS["host_reads"]
            (vc,) = api.query("bx", q)
            mv = np.array(list(models["u"].values()))
            if q.startswith("Sum"):
                assert (vc.value, vc.count) == (int(mv.sum()), len(mv))
            assert planmod.STATS["evals"] - ev0 == 1, q
            assert planmod.STATS["host_reads"] - rd0 == 1, q

    def test_filtered_sum_all_paths(self, bsi_cluster):
        cluster, models, fcols = bsi_cluster
        sel = np.array(
            [models["v"][c] for c in fcols.tolist()], np.int64
        )
        (rm,), (rh,), snap = _both(cluster, "Sum(Row(f=0), field=v)")
        assert (rm.value, rm.count) == (rh.value, rh.count)
        assert (rm.value, rm.count) == (int(sel.sum()), len(sel))
        assert snap["fallbacks"] == 0

    def test_write_visibility_through_mesh(self, bsi_cluster):
        cluster, models, _ = bsi_cluster
        api = cluster[0].api
        _set_mesh(cluster, True)
        col = 5 * SHARD_WIDTH + 123_457
        api.query("bx", f"Set({col}, u=3999)")
        models["u"][col] = 3999
        (vc,), (vh,), _ = _both(cluster, "Sum(field=u)")
        mv = np.array(list(models["u"].values()))
        assert (vc.value, vc.count) == (int(mv.sum()), len(mv))
        assert (vh.value, vh.count) == (vc.value, vc.count)
