"""Key translation tests.

Reference semantics: translate.go / boltdb/translate.go (monotonic ids from
1, persistence, replication log) and executor.go:2615-2912 (call/result
translation on keyed indexes/fields).
"""

import os

import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.translate import ReadOnlyError, TranslateStore
from pilosa_tpu.exec.executor import Executor, Pair


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_store_monotonic_ids():
    s = TranslateStore().open()
    assert s.translate_key("a") == 1
    assert s.translate_key("b") == 2
    assert s.translate_key("a") == 1
    assert s.translate_keys(["c", "a", "d"]) == [3, 1, 4]
    assert s.key_for_id(3) == "c"
    assert s.find_key("zzz") is None
    assert s.max_id() == 4
    assert len(s) == 4


def test_store_persistence(tmp_path):
    p = str(tmp_path / "keys.translate")
    s = TranslateStore(p).open()
    ids = s.translate_keys(["x", "y", "z"])
    s.close()

    s2 = TranslateStore(p).open()
    assert s2.translate_keys(["x", "y", "z"]) == ids
    assert s2.translate_key("w") == 4
    s2.close()


def test_store_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "keys.translate")
    s = TranslateStore(p).open()
    s.translate_keys(["aa", "bb"])
    s.close()
    with open(p, "ab") as f:  # simulate crash mid-append
        f.write(b"\x07\x00\x00")
    s2 = TranslateStore(p).open()
    assert s2.find_key("aa") == 1
    assert s2.find_key("bb") == 2
    assert s2.translate_key("cc") == 3
    s2.close()
    s3 = TranslateStore(p).open()
    assert s3.find_key("cc") == 3


def test_store_read_only_raises():
    s = TranslateStore(read_only=True).open()
    with pytest.raises(ReadOnlyError):
        s.translate_key("nope")


def test_store_replication_log(tmp_path):
    primary = TranslateStore(str(tmp_path / "primary")).open()
    replica = TranslateStore(str(tmp_path / "replica")).open()
    primary.translate_keys(["a", "b"])
    entries, off = primary.entries_since(0)
    replica.apply_entries(entries)
    primary.translate_key("c")
    entries2, off2 = primary.entries_since(off)
    assert [k for _, k in entries2] == ["c"]
    replica.apply_entries(entries2)
    assert replica.find_key("a") == 1
    assert replica.find_key("c") == 3


def test_store_replication_conflict_raises():
    from pilosa_tpu.core.translate import TranslateError

    primary = TranslateStore().open()
    replica = TranslateStore().open()
    # replica wrongly allocates locally (writes must forward to the primary)
    replica.translate_key("local")
    primary.translate_key("remote")
    entries, _ = primary.entries_since(0)
    with pytest.raises(TranslateError):
        replica.apply_entries(entries)


def test_store_memory_mode_offsets_are_entry_indexes():
    primary = TranslateStore().open()
    primary.translate_keys(["a", "b"])
    off = primary.write_offset
    assert off == 2
    primary.translate_key("c")
    entries, new_off = primary.entries_since(off)
    assert [k for _, k in entries] == ["c"]
    assert new_off == 3 == primary.write_offset


# ---------------------------------------------------------------------------
# end-to-end through the executor
# ---------------------------------------------------------------------------


@pytest.fixture
def keyed(tmp_path):
    h = Holder(str(tmp_path)).open()
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    yield h, Executor(h)
    h.close()


def test_set_row_with_keys(keyed):
    h, e = keyed
    assert e.execute("i", 'Set("one", f="red")') == [True]
    assert e.execute("i", 'Set("two", f="red")') == [True]
    assert e.execute("i", 'Set("one", f="blue")') == [True]
    (row,) = e.execute("i", 'Row(f="red")')
    assert row.keys == ["one", "two"]
    (cnt,) = e.execute("i", 'Count(Row(f="red"))')
    assert cnt == 2
    # unseen key reads as empty
    (row2,) = e.execute("i", 'Row(f="never")')
    assert row2.count() == 0


def test_keys_persist_across_reopen(tmp_path):
    h = Holder(str(tmp_path)).open()
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    e = Executor(h)
    e.execute("i", 'Set("col-a", f="row-a")')
    h.close()

    h2 = Holder(str(tmp_path)).open()
    e2 = Executor(h2)
    (row,) = e2.execute("i", 'Row(f="row-a")')
    assert row.keys == ["col-a"]
    # same keys resolve to the same ids after reopen
    e2.execute("i", 'Set("col-a", f="row-b")')
    (row2,) = e2.execute("i", 'Row(f="row-b")')
    assert row2.keys == ["col-a"]
    h2.close()


def test_topn_returns_keys(keyed):
    h, e = keyed
    for col in ("c1", "c2", "c3"):
        e.execute("i", f'Set("{col}", f="hot")')
    e.execute("i", 'Set("c1", f="cold")')
    (pairs,) = e.execute("i", "TopN(f, n=2)")
    assert [(p.key, p.count) for p in pairs] == [("hot", 3), ("cold", 1)]


def test_rows_returns_keys(keyed):
    h, e = keyed
    e.execute("i", 'Set("c", f="alpha")')
    e.execute("i", 'Set("c", f="beta")')
    (rows,) = e.execute("i", "Rows(f)")
    assert sorted(rows) == ["alpha", "beta"]


def test_groupby_returns_row_keys(keyed):
    h, e = keyed
    e.execute("i", 'Set("c1", f="g1")')
    e.execute("i", 'Set("c2", f="g1")')
    e.execute("i", 'Set("c1", f="g2")')
    (groups,) = e.execute("i", "GroupBy(Rows(f))")
    got = {(g.group[0].row_key, g.count) for g in groups}
    assert got == {("g1", 2), ("g2", 1)}


def test_string_key_without_keys_errors(tmp_path):
    from pilosa_tpu.exec.translation import TranslationError

    h = Holder(str(tmp_path)).open()
    idx = h.create_index("plain")
    idx.create_field("f", FieldOptions())
    e = Executor(h)
    with pytest.raises(TranslationError):
        e.execute("plain", 'Set(1, f="red")')
    h.close()


def test_groupby_previous_list_translates_keys(keyed):
    """GroupBy(previous=[...]) entries translate through each child's field
    row keys (reference executor.go:2742-2782)."""
    h, e = keyed
    for col, row in [("c1", "g1"), ("c2", "g1"), ("c1", "g2"), ("c3", "g3")]:
        e.execute("i", f'Set("{col}", f="{row}")')
    # row ids allocate in first-seen order: g1=1, g2=2, g3=3
    (groups,) = e.execute("i", 'GroupBy(Rows(f), previous=["g1"])')
    got = [(g.group[0].row_key, g.count) for g in groups]
    assert got == [("g2", 1), ("g3", 1)]
    # non-string previous entry on a keyed field is an error
    from pilosa_tpu.exec.translation import TranslationError

    with pytest.raises(TranslationError, match="must be a string"):
        e.execute("i", "GroupBy(Rows(f), previous=[3])")
