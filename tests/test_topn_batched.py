"""Batched TopN: differential vs the per-shard path + dispatch accounting.

VERDICT r2 #1 done-criteria: results identical to the per-shard host path on
randomized and adversarial-skew corpora, and a dispatch-count assertion that
the batched path issues O(1) device tallies per pass — never one per shard
(reference: fragment.go:1570-1743 top, executor.go:860-999 two-pass TopN).
"""

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec import executor as exmod
from pilosa_tpu.exec import plan as planmod
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _mk(bits, cache_size=50_000, src_bits=None, attrs=None):
    """bits: iterable of (row, col) for field f; src_bits likewise for g."""
    h = Holder().open()
    idx = h.create_index("i")
    f = idx.create_field("f", FieldOptions(cache_size=cache_size))
    if bits:
        rows = np.array([r for r, _ in bits], np.uint64)
        cols = np.array([c for _, c in bits], np.uint64)
        f.import_bits(rows, cols)
    if src_bits is not None:
        g = idx.create_field("g")
        rows = np.array([r for r, _ in src_bits], np.uint64)
        cols = np.array([c for _, c in src_bits], np.uint64)
        g.import_bits(rows, cols)
    if attrs:
        for rid, kv in attrs.items():
            f.row_attr_store.set_attrs(rid, kv)
    return h, Executor(h)


def _pairs(res):
    return [(p.id, p.count) for p in res]


def _both_paths(h, ex, pql, monkeypatch):
    """Run a query on the default (one-pass where eligible) path, the
    classic batched two-pass, and the forced per-shard path; assert the
    first two agree and return (default, serial) for the caller's check —
    a three-way differential over every TopN execution strategy."""
    fast = ex.execute("i", pql)
    with monkeypatch.context() as m:
        m.setattr(
            Executor, "_topn_local_full", lambda self, idx, c, shards: None
        )
        batched = ex.execute("i", pql)
        assert _pairs(fast[0]) == _pairs(batched[0]), pql
        m.setattr(
            Executor, "_topn_merged_batched", lambda self, idx, spec, shards: None
        )
        serial = ex.execute("i", pql)
    return fast, serial


QUERIES = [
    "TopN(f)",
    "TopN(f, n=1)",
    "TopN(f, n=3)",
    "TopN(f, n=100)",
    "TopN(f, threshold=3)",
    "TopN(f, n=2, threshold=5)",
    "TopN(f, ids=[0, 1, 2, 7])",
    "TopN(f, Row(g=0))",
    "TopN(f, Row(g=0), n=2)",
    "TopN(f, Row(g=0), threshold=3)",
    "TopN(f, Row(g=0), n=4, threshold=2)",
    "TopN(f, Row(g=0), n=3, tanimotoThreshold=30)",
    "TopN(f, Row(g=0), n=3, tanimotoThreshold=80)",
    "TopN(f, Row(g=0), ids=[1, 2, 3])",
]


class TestDifferential:
    def test_randomized(self, monkeypatch, rng):
        """Random corpus over 6 shards, zipf-ish row sizes."""
        n_shards = 6
        bits = []
        for row in range(18):
            n = int(rng.integers(1, 400) // (row + 1)) + 1
            cols = rng.integers(0, n_shards * SHARD_WIDTH, n)
            bits += [(row, int(c)) for c in cols]
        src = [(0, int(c)) for c in rng.integers(0, n_shards * SHARD_WIDTH, 500)]
        h, ex = _mk(bits, src_bits=src)
        for pql in QUERIES:
            b, s = _both_paths(h, ex, pql, monkeypatch)
            assert _pairs(b[0]) == _pairs(s[0]), pql

    def test_adversarial_skew(self, monkeypatch, rng):
        """One dominating row in one shard, heavy ties, rows in disjoint
        shard subsets, empty shard gaps."""
        bits = []
        # row 0 dominates shard 4 only
        bits += [(0, 4 * SHARD_WIDTH + i) for i in range(2000)]
        # rows 1..6 tie exactly (count 7 each), spread over shards 0..2
        for row in range(1, 7):
            bits += [(row, (i % 3) * SHARD_WIDTH + row * 50 + i) for i in range(7)]
        # rows 7..10 live only in shard 7 (gap at shards 3,5,6)
        for row in range(7, 11):
            bits += [(row, 7 * SHARD_WIDTH + row * 11 + i) for i in range(row)]
        src = [(0, 4 * SHARD_WIDTH + i) for i in range(0, 2000, 2)]
        src += [(0, i * 50) for i in range(60)]
        h, ex = _mk(bits, src_bits=src)
        for pql in QUERIES:
            b, s = _both_paths(h, ex, pql, monkeypatch)
            assert _pairs(b[0]) == _pairs(s[0]), pql

    def test_cache_eviction_approximation(self, monkeypatch, rng):
        """With a tiny rank cache, evicted rows are not candidates — the
        documented approximation must be IDENTICAL on both paths."""
        n_shards = 3
        bits = []
        for row in range(20):
            n = 21 - row
            cols = rng.integers(0, n_shards * SHARD_WIDTH, n * 3)
            bits += [(row, int(c)) for c in cols]
        h, ex = _mk(bits, cache_size=4)
        for pql in ["TopN(f)", "TopN(f, n=3)", "TopN(f, ids=[0, 15, 19])"]:
            b, s = _both_paths(h, ex, pql, monkeypatch)
            assert _pairs(b[0]) == _pairs(s[0]), pql

    def test_attr_filters(self, monkeypatch):
        bits = []
        for row in range(8):
            bits += [(row, row * 3 + i) for i in range(row + 1)]
        attrs = {r: {"cat": "a" if r % 2 else "b"} for r in range(8)}
        h, ex = _mk(bits, attrs=attrs)
        pql = 'TopN(f, n=4, attrName="cat", attrValues=["a"])'
        b, s = _both_paths(h, ex, pql, monkeypatch)
        assert _pairs(b[0]) == _pairs(s[0])
        assert all(p[0] % 2 == 1 for p in _pairs(b[0]))

    def test_attr_filters_with_src(self, monkeypatch):
        """Attr filter + filter bitmap together exercise the one-pass
        vectorized attr prune against both fallbacks."""
        bits = []
        for row in range(8):
            bits += [(row, row * 3 + i) for i in range(row + 1)]
        src = [(0, c) for c in range(0, 30)]
        attrs = {r: {"cat": "a" if r % 2 else "b"} for r in range(8)}
        h, ex = _mk(bits, src_bits=src, attrs=attrs)
        pql = 'TopN(f, Row(g=0), n=4, attrName="cat", attrValues=["a"])'
        b, s = _both_paths(h, ex, pql, monkeypatch)
        assert _pairs(b[0]) == _pairs(s[0])
        assert all(p[0] % 2 == 1 for p in _pairs(b[0]))


class TestDispatchCounts:
    def test_plain_topn_is_pure_host(self):
        """Unfiltered TopN reads only exact host metadata: ZERO device
        dispatches (the r2 bench's 273.9 ms was all host merge)."""
        bits = [(r, r * 7 + i) for r in range(10) for i in range(r + 1)]
        bits += [(r, SHARD_WIDTH + r) for r in range(10)]
        h, ex = _mk(bits)
        ex.execute("i", "TopN(f, n=5)")  # warm
        from pilosa_tpu.core.resultcache import RESULT_CACHE

        RESULT_CACHE.reset()  # the probe asserts the tally path, not the cache
        planmod.reset_stats()
        for k in exmod.TOPN_STATS:
            exmod.TOPN_STATS[k] = 0
        ex.execute("i", "TopN(f, n=5)")
        assert planmod.STATS["evals"] == 0
        assert exmod.TOPN_STATS["tally_evals"] == 0
        assert exmod.TOPN_STATS["batched"] == 2  # both passes batched
        assert exmod.TOPN_STATS["fallback"] == 0

    def test_filtered_topn_bounded_dispatches(self):
        """Filtered TopN runs as ONE pass: one stacked src eval + one
        batched tally covering both the pass-1 select and the pass-2 exact
        recount, independent of shard count (r5: the [R, S] ic matrix is
        reused host-side for pass 2 — a second dispatch+read would double
        the tunnel-RTT cost per query)."""
        n_shards = 40
        bits = []
        for row in range(12):
            bits += [
                (row, s * SHARD_WIDTH + row * 13 + i)
                for s in range(n_shards)
                for i in range(3)
            ]
        src = [(0, s * SHARD_WIDTH + i) for s in range(n_shards) for i in range(200)]
        h, ex = _mk(bits, src_bits=src)
        ex.execute("i", "TopN(f, Row(g=0), n=5)")  # warm
        from pilosa_tpu.core.resultcache import RESULT_CACHE

        RESULT_CACHE.reset()  # the probe asserts the tally path, not the cache
        planmod.reset_stats()
        for k in exmod.TOPN_STATS:
            exmod.TOPN_STATS[k] = 0
        ex.execute("i", "TopN(f, Row(g=0), n=5)")
        assert exmod.TOPN_STATS["fallback"] == 0
        assert exmod.TOPN_STATS["one_pass"] == 1
        # ONE src plan eval for the whole query (no pass-2 re-eval)
        assert planmod.STATS["evals"] == 1
        # tallies bounded by candidate chunks (dense planes + sparse
        # gather), NOT by the 40 shards, and issued once, not per pass
        assert exmod.TOPN_STATS["tally_evals"] <= 2

    def test_cache_counts_exact(self):
        """The pass-2 cardinality fast path: an unpruned rank cache is a
        complete exact row->count map; once pruned it must return None
        (callers fall back to row_counts_host)."""
        bits = [(r, r * 5 + i) for r in range(6) for i in range(r + 1)]
        h, ex = _mk(bits)
        frag = (
            h.index("i").field("f").view("standard").fragment_if_exists(0)
        )
        ids = np.array([0, 3, 5, 99], np.uint64)
        got = frag.cache_counts_exact(ids)
        assert got is not None
        want = frag.row_counts_host([0, 3, 5, 99])
        assert (got == want).all(), (got, want)
        # pruned cache -> None
        h2, ex2 = _mk(bits, cache_size=3)
        frag2 = (
            h2.index("i").field("f").view("standard").fragment_if_exists(0)
        )
        assert frag2.cache_counts_exact(ids) is None

    def test_pruned_flag_survives_sidecar_reload(self, tmp_path):
        """A pruned cache flushed to the .cache sidecar and reloaded must
        NOT reload as 'provably complete' — cache_counts_exact would
        return 0 for the pruned rows and TopN pass-2 would silently
        undercount after a restart (code-review r5 finding)."""
        from pilosa_tpu.core import cache as cachemod

        cache = cachemod.RankCache(max_size=3)
        for r in range(6):
            cache.add(r, 10 + r)
        cache.recalculate()
        assert cache.pruned
        path = str(tmp_path / "frag.cache")
        cachemod.write_cache(path, cache)
        fresh = cachemod.RankCache(max_size=3)
        assert cachemod.read_cache(path, fresh)
        assert fresh.pruned  # the flag rode the sidecar
        # and an unpruned cache round-trips as unpruned
        ok = cachemod.RankCache(max_size=50)
        ok.add(1, 7)
        path2 = str(tmp_path / "ok.cache")
        cachemod.write_cache(path2, ok)
        fresh2 = cachemod.RankCache(max_size=50)
        assert cachemod.read_cache(path2, fresh2)
        assert not fresh2.pruned

    def test_cache_counts_exact_none_after_restart_when_pruned(self, tmp_path):
        """End-to-end: fragment with more rows than cache_size, snapshot +
        close + reopen — the fast path must refuse (None), not undercount."""
        from pilosa_tpu.core.field import FieldOptions
        from pilosa_tpu.core.holder import Holder

        d = str(tmp_path / "h")
        h = Holder(d).open()
        idx = h.create_index("i")
        f = idx.create_field("f", FieldOptions(cache_size=4))
        bits = [(r, r * 3 + i) for r in range(10) for i in range(r + 1)]
        rows = np.array([r for r, _ in bits], np.uint64)
        cols = np.array([c for _, c in bits], np.uint64)
        f.import_bits(rows, cols)
        frag = f.view("standard").fragment_if_exists(0)
        frag.snapshot()  # WAL truncated: sidecar will be trusted on reopen
        h.close()
        h2 = Holder(d).open()
        frag2 = (
            h2.index("i").field("f").view("standard").fragment_if_exists(0)
        )
        ids = np.arange(10, dtype=np.uint64)
        assert frag2.cache_counts_exact(ids) is None
        # authoritative counts still exact
        want = np.array([r + 1 for r in range(10)], np.uint64)
        assert (frag2.row_counts_host(list(range(10))) == want).all()
        h2.close()

    def test_snapshot_flushes_sidecar_before_wal_truncate(self, tmp_path, monkeypatch):
        """Crash-window ordering: the cache sidecar must hit disk BEFORE
        the WAL truncates — open() only trusts the sidecar when the WAL
        replays nothing, so a crash in between must leave a non-empty WAL
        (recalculate path), never a stale 'complete' sidecar serving
        wrong exact counts (code-review r5 finding)."""
        from pilosa_tpu.core import fragment as fragmod
        from pilosa_tpu.core import wal as walmod
        from pilosa_tpu.core.holder import Holder

        h = Holder(str(tmp_path / "h")).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        f.import_bits(np.array([1, 2], np.uint64), np.array([5, 9], np.uint64))
        frag = f.view("standard").fragment_if_exists(0)
        order = []
        orig_flush = fragmod.Fragment.flush_cache
        orig_trunc = walmod.WalWriter.truncate
        monkeypatch.setattr(
            fragmod.Fragment, "flush_cache",
            lambda self: (order.append("flush"), orig_flush(self))[1],
        )
        monkeypatch.setattr(
            walmod.WalWriter, "truncate",
            lambda self: (order.append("truncate"), orig_trunc(self))[1],
        )
        frag.snapshot()
        assert order.index("flush") < order.index("truncate"), order
        h.close()

    def test_row_count_is_o1(self):
        """RowBits cardinality must be maintained, not recomputed (plain
        TopN pass 2 does n_shards x n_candidates count() calls)."""
        from pilosa_tpu.core.rowstore import RowBits

        rb = RowBits(SHARD_WIDTH)
        rng = np.random.default_rng(3)
        ref = set()
        for _ in range(8):
            new = rng.integers(0, SHARD_WIDTH, 40_000).astype(np.uint32)
            rb.add(new)
            ref |= set(int(x) for x in new)
            assert rb.count() == len(ref)
            gone = rng.integers(0, SHARD_WIDTH, 10_000).astype(np.uint32)
            rb.discard(gone)
            ref -= set(int(x) for x in gone)
            assert rb.count() == len(ref)
        words = np.zeros(SHARD_WIDTH // 32, np.uint32)
        words[:100] = 0xFFFFFFFF
        rb.union_words(words)
        ref |= set(range(3200))
        assert rb.count() == len(ref)


class TestMinMaxRowBatched:
    def test_differential_and_dispatch_count(self, monkeypatch, rng):
        """Filtered MinRow/MaxRow matches the per-shard path on a random
        corpus and issues O(1) tallies, not one dispatch per shard."""
        n_shards = 30
        bits = []
        for row in (2, 5, 9, 14, 30):
            cols = rng.integers(0, n_shards * SHARD_WIDTH, 300)
            bits += [(row, int(c)) for c in cols]
        src = [(0, int(c)) for c in rng.integers(0, n_shards * SHARD_WIDTH, 4000)]
        h, ex = _mk(bits, src_bits=src)
        for pql in ("MinRow(Row(g=0), field=f)", "MaxRow(Row(g=0), field=f)"):
            got = ex.execute("i", pql)
            with monkeypatch.context() as m:
                m.setattr(
                    Executor,
                    "_min_max_row_batched",
                    lambda self, idx, v, fc, sl, is_min: None,
                )
                want = ex.execute("i", pql)
            assert got == want, pql
        exmod.TOPN_STATS["tally_evals"] = 0
        ex.execute("i", "MinRow(Row(g=0), field=f)")
        assert 0 < exmod.TOPN_STATS["tally_evals"] <= 2

    def test_filter_matches_nothing(self, rng):
        bits = [(r, r * 11 + i) for r in (3, 7) for i in range(5)]
        src = [(0, SHARD_WIDTH * 2 + 1)]  # disjoint from all rows
        h, ex = _mk(bits, src_bits=src)
        assert ex.execute("i", "MinRow(Row(g=0), field=f)") == [
            {"id": 0, "count": 0}
        ]

    def test_unfiltered_still_host(self, rng):
        bits = [(r, r * 11 + i) for r in (3, 7, 12) for i in range(4)]
        h, ex = _mk(bits)
        assert ex.execute("i", "MinRow(field=f)")[0]["id"] == 3
        assert ex.execute("i", "MaxRow(field=f)")[0]["id"] == 12
