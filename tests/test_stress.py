"""Concurrency stress + shard-width matrix (VERDICT r2 #7a/#7c).

The reference runs its whole suite under -race and re-runs CI at
SHARD_WIDTH=22 (SURVEY §4). Python has no race detector, so the stress
test drives the lock discipline (fragment._mu, devcache._mu, resize/_
topology swaps) under real contention — concurrent imports + queries +
anti-entropy against one live cluster — and asserts invariants at the end;
the width matrix re-runs core suites in subprocesses at exponents 16/22.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


@pytest.mark.slow
def test_concurrent_imports_queries_ae():
    """Writers, readers and anti-entropy hammer one 3-node cluster
    concurrently; nothing may raise, and the final state must equal the
    union of everything written on every node."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("st")
        api.create_field("st", "f", {"type": "set"})
        api.create_field(
            "st", "v", {"type": "int", "min": 0, "max": 1_000_000}
        )
        stop = threading.Event()
        errors: list = []
        written_cols: list = [set() for _ in range(3)]

        def writer(wid: int):
            rng = np.random.default_rng(100 + wid)
            try:
                while not stop.is_set():
                    cols = rng.integers(0, 8 * SHARD_WIDTH, 200).astype(np.uint64)
                    # rotate the entry node: writes land via different
                    # coordinators and replica fan-outs
                    node = c[wid % 3]
                    node.api.import_bits(
                        "st", "f", np.full(len(cols), wid, np.uint64), cols
                    )
                    written_cols[wid] |= {int(x) for x in cols}
                    node.api.import_values(
                        "st", "v", cols[:50], rng.integers(0, 1_000_000, 50)
                    )
            except Exception as e:  # noqa: BLE001 - collected for assert
                errors.append(("writer", wid, repr(e)))

        def reader(rid: int):
            try:
                while not stop.is_set():
                    node = c[rid % 3]
                    node.api.query("st", f"Count(Row(f={rid % 3}))")
                    node.api.query("st", "TopN(f, n=3)")
                    node.api.query("st", "Sum(field=v)")
            except Exception as e:  # noqa: BLE001
                errors.append(("reader", rid, repr(e)))

        def ae():
            try:
                while not stop.is_set():
                    for node in c.nodes:
                        node.sync_holder()
                    time.sleep(0.05)
            except Exception as e:  # noqa: BLE001
                errors.append(("ae", 0, repr(e)))

        threads = (
            [threading.Thread(target=writer, args=(i,)) for i in range(3)]
            + [threading.Thread(target=reader, args=(i,)) for i in range(3)]
            + [threading.Thread(target=ae)]
        )
        for t in threads:
            t.start()
        time.sleep(6.0)
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "stress thread wedged"
        assert not errors, errors[:5]
        # settle: one final AE pass from every node, then every node must
        # agree with the exact union of what the writers recorded
        for node in c.nodes:
            node.sync_holder()
        for wid in range(3):
            expect = len(written_cols[wid])
            for node in c.nodes:
                (cnt,) = node.api.query("st", f"Count(Row(f={wid}))")
                assert cnt == expect, (node.node.id, wid, cnt, expect)
        # devcache bookkeeping survived the churn
        from pilosa_tpu.core.devcache import DEVICE_CACHE

        assert DEVICE_CACHE.bytes_used >= 0
        assert DEVICE_CACHE.bytes_used <= DEVICE_CACHE.budget_bytes * 2


# ---------------------------------------------------------------------------
# shard-width matrix (CI re-run at SHARD_WIDTH=22; SURVEY §4)
# ---------------------------------------------------------------------------

_CORE_SUITES = [
    "tests/test_storage.py",
    "tests/test_executor.py",
    "tests/test_roaring_io.py",
    "tests/test_topn_batched.py",  # r5 gather-tally bit packing
    "tests/test_merge.py",  # ISSUE 9 cross-fragment merge equivalence
    "tests/test_meshexec.py",  # ISSUE 10 mesh-group differential equivalence
    "tests/test_bsistream.py",  # ISSUE 15 plane-streamed BSI differential
]


@pytest.mark.slow
@pytest.mark.parametrize("exponent", ["16", "22"])
def test_shard_width_matrix(exponent):
    """Core suites must pass at non-default shard widths — catching any
    width-hardcoding (the reference's SHARD_WIDTH=22 CI job)."""
    env = dict(os.environ)
    env["PILOSA_TPU_SHARD_WIDTH_EXPONENT"] = exponent
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q"] + _CORE_SUITES,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]


@pytest.mark.slow
def test_paranoia_suite():
    """Storage + executor suites under PILOSA_TPU_PARANOIA=1: the invariant
    guards must hold on every mutation path (roaringparanoia CI analog)."""
    env = dict(os.environ)
    env["PILOSA_TPU_PARANOIA"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         "tests/test_storage.py", "tests/test_executor.py"],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
