"""Rank-cache semantics + persistence + TopN integration.

Reference test model: cache_test.go (ranked/lru bounds), fragment cache
persistence (.cache files), api RecalculateCaches."""

import numpy as np
import pytest

from pilosa_tpu.core import cache as cachemod
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.field import FieldOptions


def test_rank_cache_orders_and_bounds():
    c = cachemod.RankCache(max_size=3)
    c.bulk_add([(1, 10), (2, 30), (3, 20), (4, 5), (5, 40)])
    assert [rid for rid, _ in c.top()] == [5, 2, 3]
    assert len(c) == 3
    # evicted row is gone even if re-queried
    assert c.get(4) == 0
    # count update reorders
    c.add(3, 99)
    assert c.top()[0] == (3, 99)
    # zero count evicts
    c.add(3, 0)
    assert c.get(3) == 0


def test_rank_cache_tie_break_lowest_id():
    c = cachemod.RankCache()
    c.bulk_add([(9, 7), (2, 7), (5, 7)])
    assert [rid for rid, _ in c.top()] == [2, 5, 9]


def test_lru_cache_evicts_oldest():
    c = cachemod.LRUCache(max_size=2)
    c.add(1, 10)
    c.add(2, 20)
    c.add(1, 11)  # touch 1
    c.add(3, 30)  # evicts 2
    assert c.get(2) == 0
    assert sorted(c.ids()) == [1, 3]


def test_no_cache_noop():
    c = cachemod.make_cache("none")
    c.add(1, 5)
    assert c.top() == [] and len(c) == 0


def test_cache_file_round_trip(tmp_path):
    c = cachemod.RankCache()
    c.bulk_add([(7, 70), (8, 80)])
    p = str(tmp_path / "x.cache")
    cachemod.write_cache(p, c)
    c2 = cachemod.RankCache()
    assert cachemod.read_cache(p, c2)
    assert c2.top() == [(8, 80), (7, 70)]
    # corrupt file is rejected, cache untouched
    with open(p, "wb") as f:
        f.write(b"garbage!")
    c3 = cachemod.RankCache()
    assert not cachemod.read_cache(p, c3)


def test_fragment_maintains_cache(tmp_path):
    frag = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0).open()
    frag.bulk_import(np.array([1, 1, 1, 2], dtype=np.uint64),
                     np.array([10, 11, 12, 10], dtype=np.uint64))
    assert frag.cache.top() == [(1, 3), (2, 1)]
    frag.clear_bit(1, 11)
    assert frag.cache.get(1) == 2
    # persists through close/reopen via the .cache sidecar
    frag.close()
    frag2 = Fragment(str(tmp_path / "0"), "i", "f", "standard", 0).open()
    assert frag2.cache.top() == [(1, 2), (2, 1)]
    frag2.close()


def test_fragment_cache_rebuilt_without_sidecar(tmp_path):
    frag = Fragment(str(tmp_path / "1"), "i", "f", "standard", 1).open()
    frag.bulk_import(np.array([5, 5], dtype=np.uint64),
                     np.array([1, 2], dtype=np.uint64))
    frag.close()
    import os

    os.remove(str(tmp_path / "1.cache"))
    frag2 = Fragment(str(tmp_path / "1"), "i", "f", "standard", 1).open()
    assert frag2.cache.top() == [(5, 2)]
    frag2.close()


def test_holder_recalculate_and_flush(tmp_path):
    h = Holder(str(tmp_path)).open()
    idx = h.create_index("i")
    f = idx.create_field("f", FieldOptions(type="set"))
    f.set_bit(3, 100)
    f.set_bit(3, 200)
    frag = f.view().fragment_if_exists(0)
    frag.cache.clear()  # simulate drift
    h.recalculate_caches()
    assert frag.cache.top() == [(3, 2)]
    h.flush_caches()
    import os

    assert os.path.exists(frag.cache_path)
    h.close()


def test_bsi_views_have_no_cache(tmp_path):
    h = Holder(str(tmp_path)).open()
    idx = h.create_index("i")
    f = idx.create_field("v", FieldOptions(type="int", min=0, max=1000))
    f.set_value(1, 42)
    v = f.view(f.bsi_view_name())
    frag = v.fragment_if_exists(0)
    assert frag.cache.cache_type == "none"
    h.close()


@pytest.fixture()
def topn_env():
    from pilosa_tpu.testing import ClusterHarness

    with ClusterHarness(1, in_memory=True) as c:
        yield c[0]


def test_topn_uses_cache_counts(topn_env):
    srv = topn_env
    srv.api.create_index("ti")
    srv.api.create_field("ti", "tf", options={"type": "set", "cache_size": 100})
    rows = np.repeat(np.arange(10, dtype=np.uint64), np.arange(1, 11))
    cols = np.arange(len(rows), dtype=np.uint64)
    srv.api.import_bits("ti", "tf", rows, cols)
    res = srv.api.query("ti", "TopN(tf, n=3)")
    pairs = res[0]
    assert [(p.id, p.count) for p in pairs] == [(9, 10), (8, 9), (7, 8)]
    # cache candidate pruning: evicted rows are not candidates
    frag = srv.holder.index("ti").field("tf").view().fragment_if_exists(0)
    assert frag is not None and len(frag.cache) == 10


def test_topn_filtered_still_exact(topn_env):
    srv = topn_env
    srv.api.create_index("tj")
    srv.api.create_field("tj", "tg", options={"type": "set"})
    srv.api.create_field("tj", "filt", options={"type": "set"})
    # row 1: cols 0..9 ; row 2: cols 0..4 ; filter row 0: cols 0..2
    srv.api.import_bits("tj", "tg",
                        np.concatenate([np.full(10, 1), np.full(5, 2)]).astype(np.uint64),
                        np.concatenate([np.arange(10), np.arange(5)]).astype(np.uint64))
    srv.api.import_bits("tj", "filt", np.zeros(3, dtype=np.uint64),
                        np.arange(3, dtype=np.uint64))
    res = srv.api.query("tj", "TopN(tg, Row(filt=0), n=2)")
    assert [(p.id, p.count) for p in res[0]] == [(1, 3), (2, 3)]


def test_stale_sidecar_ignored_after_wal_replay(tmp_path):
    # sidecar flushed, then more WAL writes, then crash (no close-flush):
    # reopen must not trust the stale sidecar
    frag = Fragment(str(tmp_path / "2"), "i", "f", "standard", 2).open()
    frag.set_bit(0, 5)
    frag.flush_cache()
    frag.set_bit(0, 6)
    frag.set_bit(0, 7)
    frag._wal.close()  # simulate crash: skip close()'s cache flush
    frag._wal = None
    frag2 = Fragment(str(tmp_path / "2"), "i", "f", "standard", 2).open()
    assert frag2.cache.top() == [(0, 3)]
    frag2.close()


def test_lru_bulk_add_bounded():
    c = cachemod.LRUCache(max_size=2)
    c.bulk_add([(i, i + 1) for i in range(10)])
    assert len(c) == 2


def test_invalid_cache_type_rejected_at_creation(topn_env):
    import urllib.error
    import urllib.request
    import json

    uri = topn_env.node.uri
    req = urllib.request.Request(
        f"{uri}/index/badc", data=b"{}", method="POST",
        headers={"Content-Type": "application/json"},
    )
    urllib.request.urlopen(req, timeout=10).read()
    body = json.dumps({"options": {"cacheType": "rankedd"}}).encode()
    req = urllib.request.Request(
        f"{uri}/index/badc/field/bf", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("expected HTTP 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
