"""Multi-tenant QoS enforcement (pilosa_tpu/sched/tenants.py and its
enforcement points): token-bucket units on an injected clock, override
parsing, admission-time rate/quota shedding on both lanes with derived
Retry-After (the shed-retry-after knob as a floor), second-level
per-index SFQ dequeue order inside a WFQ class, quota-first eviction in
the device cache (including zombie-pinned attribution) and the result
cache, prefetcher gating, X-Pilosa-Quota-* response headers, and the
@slow two-tenant overload soak: the abusive index sheds 429 while
well-behaved tenants keep their latency and their cache residency.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DeviceCache, new_owner_token
from pilosa_tpu.core.resultcache import ResultCache
from pilosa_tpu.sched.admission import AdmissionController, ShedError
from pilosa_tpu.sched.cost import QueryCost
from pilosa_tpu.sched.tenants import (
    TenantPolicy,
    TokenBucket,
    parse_overrides,
)
from pilosa_tpu.testing import ClusterHarness
from pilosa_tpu.utils.stats import StatsClient


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.002)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# token bucket (injected clock, no sleeps)
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_starts_full_and_denies_with_refill_seconds(self):
        b = TokenBucket(rate=2.0, burst=2.0, now=0.0)
        assert b.take(1.0, 0.0) == 0.0
        assert b.take(1.0, 0.0) == 0.0
        # empty: one token refills in 1/rate seconds
        assert b.take(1.0, 0.0) == pytest.approx(0.5)

    def test_refills_at_rate_and_caps_at_burst(self):
        b = TokenBucket(rate=2.0, burst=2.0, now=0.0)
        assert b.take(2.0, 0.0) == 0.0
        assert b.take(1.0, 0.25) > 0.0  # only 0.5 tokens back
        assert b.take(1.0, 0.5) == 0.0
        # idling far past the burst window banks nothing extra
        assert b.take(2.0, 100.0) == 0.0
        assert b.take(0.5, 100.0) > 0.0

    def test_refund_clamps_to_burst(self):
        b = TokenBucket(rate=1.0, burst=1.0, now=0.0)
        b.refund(5.0)
        assert b.tokens == 1.0

    def test_peek_consumes_nothing(self):
        b = TokenBucket(rate=1.0, burst=1.0, now=0.0)
        assert b.peek(1.0, 0.0)
        assert b.peek(1.0, 0.0)  # still there
        assert b.take(1.0, 0.0) == 0.0
        assert not b.peek(1.0, 0.0)


# ---------------------------------------------------------------------------
# override parsing (operator config: malformed entries must raise)
# ---------------------------------------------------------------------------


class TestParseOverrides:
    def test_parses_multi_knob_entries(self):
        got = parse_overrides(
            ["a:qps=5;bytes-per-s=1e6", "b:hbm-bytes=65536"]
        )
        assert got == {
            "a": {"qps": 5.0, "bytes-per-s": 1e6},
            "b": {"hbm-bytes": 65536.0},
        }

    def test_blank_entries_skipped(self):
        assert parse_overrides(["", "  "]) == {}

    def test_malformed_entries_raise(self):
        for bad in (
            "no-colon-here",
            ":qps=1",
            "a:frobs=1",
            "a:qps=fast",
            "a:qps",
        ):
            with pytest.raises(ValueError):
                parse_overrides([bad])


# ---------------------------------------------------------------------------
# TenantPolicy units
# ---------------------------------------------------------------------------


class TestTenantPolicy:
    def test_limits_merge_overrides_over_defaults(self):
        pol = TenantPolicy(
            default_qps=10.0,
            default_hbm_bytes=1000,
            overrides=["a:qps=2;cache-bytes=64"],
        )
        a = pol.limits("a")
        assert a.qps == 2.0
        assert a.hbm_bytes == 1000  # default fills the unlisted knob
        assert a.cache_bytes == 64
        b = pol.limits("b")
        assert b.qps == 10.0 and b.cache_bytes == 0

    def test_any_limits(self):
        assert not TenantPolicy().any_limits()
        assert TenantPolicy(default_cache_bytes=1).any_limits()
        assert TenantPolicy(overrides=["a:qps=1"]).any_limits()

    def test_quota_maps(self):
        pol = TenantPolicy(
            default_hbm_bytes=100,
            default_cache_bytes=200,
            overrides=["a:hbm-bytes=7", "b:cache-bytes=9"],
        )
        assert pol.hbm_quota_map() == (100, {"a": 7})
        assert pol.cache_quota_map() == (200, {"b": 9})

    def test_qps_denial_and_refill(self):
        clk = FakeClock()
        pol = TenantPolicy(default_qps=1.0, clock=clk)
        assert pol.acquire("a", 0) is None  # burst token
        denial = pol.acquire("a", 0)
        assert denial is not None
        assert denial.reason == "rate" and denial.limit == "qps"
        assert denial.retry_after == pytest.approx(1.0)
        clk.advance(1.0)
        assert pol.acquire("a", 0) is None

    def test_byte_denial_refunds_the_qps_token(self):
        clk = FakeClock()
        pol = TenantPolicy(
            default_qps=2.0, default_bytes_per_s=100.0, clock=clk
        )
        assert pol.acquire("a", 60) is None
        # second query's bytes don't fit (40 tokens left) — the shed
        # must consume NEITHER budget, so the qps token comes back
        denial = pol.acquire("a", 60)
        assert denial is not None
        assert denial.reason == "bytes" and denial.limit == "bytes-per-s"
        assert denial.retry_after == pytest.approx(0.2)
        # qps burst was 2: one spent on the grant; without the refund
        # this zero-byte acquire would be a rate denial
        assert pol.acquire("a", 0) is None

    def test_oversized_byte_estimate_charged_at_burst(self):
        clk = FakeClock()
        pol = TenantPolicy(default_bytes_per_s=100.0, clock=clk)
        # heavier than the whole bucket: charged the burst, not denied
        # forever (single-oversized rule)
        assert pol.acquire("a", 10_000) is None
        denial = pol.acquire("a", 1)
        assert denial is not None and denial.reason == "bytes"

    def test_throttled_peek_consumes_nothing(self):
        clk = FakeClock()
        pol = TenantPolicy(default_qps=1.0, clock=clk)
        assert not pol.throttled("a")
        assert pol.acquire("a", 0) is None
        assert pol.throttled("a")
        assert pol.throttled("a")  # still just a peek
        clk.advance(1.0)
        assert not pol.throttled("a")
        assert pol.throttled(None) is False

    def test_unlimited_and_indexless_create_no_buckets(self):
        pol = TenantPolicy(default_qps=1.0)
        assert pol.acquire(None, 50) is None
        assert pol.bucket_count() == 0
        unlim = TenantPolicy()
        assert unlim.acquire("a", 50) is None
        assert unlim.bucket_count() == 0

    def test_drop_index_gcs_bucket_state(self):
        pol = TenantPolicy(default_qps=1.0)
        pol.acquire("a", 0)
        pol.acquire("b", 0)
        assert pol.bucket_count() == 2
        pol.drop_index("a")
        assert pol.bucket_count() == 1


# ---------------------------------------------------------------------------
# admission enforcement (both lanes, injected clock)
# ---------------------------------------------------------------------------


def _controller(clk, policy, **kw):
    kw.setdefault("max_concurrent", 2)
    kw.setdefault("stats", StatsClient())
    return AdmissionController(clock=clk, tenants=policy, **kw)


class TestAdmissionEnforcement:
    def test_rate_shed_carries_reason_quota_and_derived_retry_after(self):
        clk = FakeClock()
        ctl = _controller(
            clk, TenantPolicy(default_qps=1.0, clock=clk), retry_after=0.25
        )
        t = ctl.admit(index="t")
        with pytest.raises(ShedError) as ei:
            ctl.admit(index="t")
        e = ei.value
        assert e.reason == "rate"
        assert e.quota_limit == "qps" and e.quota_value == 1.0
        # derived refill (1s) dominates the 0.25 floor
        assert e.retry_after == pytest.approx(1.0)
        snap = ctl.stats.registry.snapshot()
        assert (
            snap.get("sched.shed;class:interactive,index:t,reason:rate")
            == 1
        )
        t.release()
        clk.advance(1.0)
        ctl.admit(index="t").release()
        assert ctl.pending() == (0, 0)

    def test_retry_after_knob_floors_the_derived_value(self):
        clk = FakeClock()
        ctl = _controller(
            clk, TenantPolicy(default_qps=1.0, clock=clk), retry_after=5.0
        )
        t = ctl.admit(index="t")
        with pytest.raises(ShedError) as ei:
            ctl.admit(index="t")
        assert ei.value.retry_after == pytest.approx(5.0)
        t.release()

    def test_rate_buckets_charge_the_leg_lane_too(self):
        clk = FakeClock()
        ctl = _controller(clk, TenantPolicy(default_qps=1.0, clock=clk))
        t = ctl.admit(index="t", leg=True)
        with pytest.raises(ShedError) as ei:
            ctl.admit(index="t", leg=True)
        assert ei.value.reason == "rate"
        t.release()
        assert ctl.pending() == (0, 0)

    def test_untenanted_requests_are_never_rate_limited(self):
        clk = FakeClock()
        ctl = _controller(clk, TenantPolicy(default_qps=1.0, clock=clk))
        for _ in range(5):
            ctl.admit(index=None).release()

    def test_inflight_byte_quota_both_lanes(self):
        clk = FakeClock()
        pol = TenantPolicy(default_inflight_bytes=100, clock=clk)
        ctl = _controller(clk, pol, max_concurrent=4)
        t1 = ctl.admit(index="t", cost=QueryCost(device_bytes=80))
        with pytest.raises(ShedError) as ei:
            ctl.admit(index="t", cost=QueryCost(device_bytes=40))
        e = ei.value
        assert e.reason == "bytes" and e.quota_limit == "inflight-bytes"
        assert e.quota_usage == 80.0 and e.quota_value == 100.0
        # the leg lane polices the same quota on fan-out peers
        with pytest.raises(ShedError) as ei:
            ctl.admit(index="t", cost=QueryCost(device_bytes=40), leg=True)
        assert ei.value.quota_limit == "inflight-bytes"
        # another tenant is unaffected
        ctl.admit(index="u", cost=QueryCost(device_bytes=40)).release()
        t1.release()
        ctl.admit(index="t", cost=QueryCost(device_bytes=40)).release()
        assert ctl.pending() == (0, 0)

    def test_single_query_over_whole_quota_runs_alone(self):
        clk = FakeClock()
        pol = TenantPolicy(default_inflight_bytes=100, clock=clk)
        ctl = _controller(clk, pol, max_concurrent=4)
        big = ctl.admit(index="t", cost=QueryCost(device_bytes=500))
        with pytest.raises(ShedError):
            ctl.admit(index="t", cost=QueryCost(device_bytes=1))
        big.release()
        assert ctl.pending() == (0, 0)

    def test_second_level_sfq_interleaves_same_class_tenants(self):
        """Three queued queries from index a and one from b (same class)
        must NOT drain FIFO: b dequeues right after a's first grant."""
        ctl = AdmissionController(max_concurrent=1, stats=StatsClient())
        filler = ctl.admit(cls="batch", index="filler")
        order = []
        olock = threading.Lock()
        threads = []

        def run(tag, index):
            def go():
                t = ctl.admit(cls="batch", index=index)
                with olock:
                    order.append(tag)
                time.sleep(0.01)
                t.release()

            th = threading.Thread(target=go, daemon=True)
            th.start()
            threads.append(th)

        # enqueue one at a time so arrival order is deterministic
        for tag, index in [
            ("a1", "a"), ("a2", "a"), ("a3", "a"), ("b1", "b")
        ]:
            n = ctl.queue_depth()
            run(tag, index)
            _wait_until(
                lambda n=n: ctl.queue_depth() == n + 1, what="enqueue"
            )
        filler.release()
        for th in threads:
            th.join(10)
        # SFQ: a1 (lowest virtual time, arrived first), then b1 at equal
        # footing beats a2/a3 whose index already banked service
        assert order == ["a1", "b1", "a2", "a3"], order
        assert ctl.pending() == (0, 0)

    def test_throttled_tenant_is_not_prefetch_warmed(self):
        clk = FakeClock()
        pol = TenantPolicy(default_qps=1.0, clock=clk)
        ctl = _controller(clk, pol, max_concurrent=1)
        offers = []

        class FakePrefetcher:
            def offer(self, warm):
                offers.append(warm)
                return True

        ctl.prefetcher = FakePrefetcher()
        # saturate so any arrival would wait (the offer precondition)
        slot = ctl.admit(index="other")
        assert ctl.maybe_prefetch(lambda: None, index="cold") is True
        # spend cold's burst: now throttled -> never offered
        pol.acquire("cold", 0)
        assert ctl.maybe_prefetch(lambda: None, index="cold") is False
        assert len(offers) == 1
        slot.release()

    def test_drop_index_gcs_policy_buckets(self):
        clk = FakeClock()
        pol = TenantPolicy(default_qps=100.0, clock=clk)
        ctl = _controller(clk, pol)
        ctl.admit(index="gone").release()
        assert pol.bucket_count() == 1
        ctl.drop_index("gone")
        assert pol.bucket_count() == 0


# ---------------------------------------------------------------------------
# device-cache residency quotas (quota-first eviction)
# ---------------------------------------------------------------------------


def _arr(words):
    return np.zeros(words, np.uint32)  # 4 bytes each


class TestDevcacheQuota:
    def test_over_quota_owner_pays_before_in_quota_tenants(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(overrides={"a": 600})
        t = new_owner_token()
        c.put((t, "b0"), _arr(64), index="b")  # 256 B, no quota
        c.put((t, "a0"), _arr(64), index="a")
        c.put((t, "a1"), _arr(64), index="a")
        # third insert pushes a to 768 B > 600: its own LRU head goes,
        # b's entry untouched, global budget never under pressure
        c.put((t, "a2"), _arr(64), index="a")
        assert c.get((t, "a0")) is None
        assert c.get((t, "a1")) is not None
        assert c.get((t, "b0")) is not None
        assert c.quota_evictions == 1
        assert c.quota_evictions_by_index() == {"a": 1}
        assert c.stats_snapshot()["quota_evictions"] == 1

    def test_default_quota_applies_to_every_index(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(default_bytes=300)
        t = new_owner_token()
        for idx in ("a", "b"):
            c.put((t, idx, 0), _arr(64), index=idx)
            c.put((t, idx, 1), _arr(64), index=idx)
        # each index independently held to 300 B
        for idx in ("a", "b"):
            assert c.get((t, idx, 0)) is None, idx
            assert c.get((t, idx, 1)) is not None, idx
        assert c.quota_evictions_by_index() == {"a": 1, "b": 1}

    def test_unattributed_entries_are_not_a_tenant(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(default_bytes=100)
        t = new_owner_token()
        c.put((t, 0), _arr(64))  # "-" bucket
        c.put((t, 1), _arr(64))
        assert len(c) == 2 and c.quota_evictions == 0

    def test_oversized_single_entry_kept_while_alone(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(overrides={"a": 100})
        t = new_owner_token()
        c.put((t, "big"), _arr(64), index="a")  # 256 B > quota
        assert c.get((t, "big")) is not None  # all the index holds
        c.put((t, "next"), _arr(8), index="a")
        # more arrived: the oversized entry goes (LRU first)
        assert c.get((t, "big")) is None
        assert c.get((t, "next")) is not None

    def test_configure_quotas_settles_immediately(self):
        c = DeviceCache(budget_bytes=100_000)
        t = new_owner_token()
        c.put((t, 0), _arr(64), index="a")
        c.put((t, 1), _arr(64), index="a")
        c.configure_quotas(overrides={"a": 300})
        assert c.get((t, 0)) is None
        assert c.get((t, 1)) is not None

    def test_pinned_entries_survive_quota_pressure(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(overrides={"a": 300})
        t = new_owner_token()
        c.put((t, 0), _arr(64), index="a")
        assert c.pin_if_present((t, 0))
        c.put((t, 1), _arr(64), index="a")
        # the pinned entry is skipped; the fresh one is `keep`; the
        # quota overshoots transiently like the global budget does
        assert c.get((t, 0)) is not None
        assert c.get((t, 1)) is not None
        c.unpin((t, 0))
        c.put((t, 2), _arr(8), index="a")
        # pins released: pressure settles on the owner's LRU order
        assert c.get((t, 0)) is None
        c.unpin_all([])

    def test_zombie_pinned_bytes_count_against_the_owner(self):
        """Invalidated-while-pinned device memory is still held on the
        tenant's behalf: its bytes weigh in the quota pass until the
        last unpin."""
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(overrides={"a": 300})
        t = new_owner_token()
        c.put((t, 0), _arr(64), index="a")  # 256 B
        assert c.pin_if_present((t, 0))
        c.invalidate((t, 0))  # zombie: gone from lookup, bytes held
        assert c.index_resident_bytes()["a"] == 256
        c.put((t, 1), _arr(32), index="a")  # live 128 + zombie 256 > 300
        c.put((t, 2), _arr(8), index="a")
        # the zombie pushed the owner over: its LIVE lru entry paid
        assert c.get((t, 1)) is None
        assert c.quota_evictions_by_index()["a"] >= 1
        c.unpin((t, 0))
        assert "a" not in c.index_resident_bytes() or (
            c.index_resident_bytes()["a"] < 256
        )

    def test_drop_index_attribution_gcs_ledger_keeps_override(self):
        c = DeviceCache(budget_bytes=100_000)
        c.configure_quotas(overrides={"a": 300})
        t = new_owner_token()
        for i in range(3):
            c.put((t, i), _arr(64), index="a")
        assert c.quota_evictions_by_index() == {"a": 2}
        c.invalidate_owner(t)
        c.drop_index_attribution("a")
        assert c.quota_evictions_by_index() == {}
        # the OVERRIDE is operator config: a recreated index is still
        # held to it
        t2 = new_owner_token()
        for i in range(3):
            c.put((t2, i), _arr(64), index="a")
        assert c.quota_evictions_by_index() == {"a": 2}


# ---------------------------------------------------------------------------
# result-cache tenant quotas
# ---------------------------------------------------------------------------


def _vec(token, shards=(0,), versions=(0,)):
    return (("v", "", "f", "standard", token, tuple(shards), tuple(versions)),)


class TestResultCacheQuota:
    def _cache(self, **kw):
        rc = ResultCache()
        rc.configure(budget_bytes=1 << 20, **kw)
        return rc

    def test_quota_first_eviction_spares_other_tenants(self):
        rc = self._cache()
        rc.put(("b", "q", (0,), False), "count", "idx_b", "q", 1, _vec(1))
        quota = rc.stats_snapshot()["by_index"]["idx_b"] * 2
        rc.configure(tenant_overrides={"idx_a": quota})
        for i in range(4):
            rc.put(
                (i, f"q{i}", (0,), False), "count", "idx_a", f"q{i}", i,
                _vec(i),
            )
        snap = rc.stats_snapshot()
        assert snap["by_index"]["idx_a"] <= quota
        assert snap["by_index"]["idx_b"] > 0  # untouched
        assert snap["quota_evictions"] >= 1
        assert snap["quota_evictions_by_index"]["idx_a"] >= 1
        # the last-stored entries survived (LRU within the owner)
        assert rc.get((3, "q3", (0,), False), _vec(3))[0]
        assert rc.get((0, "q0", (0,), False), _vec(0), recount=False)[0] is False

    def test_entry_bigger_than_quota_never_stored(self):
        rc = self._cache(tenant_default_bytes=8)
        rc.put(("k", "q", (0,), False), "count", "i", "q", 5, _vec(1))
        assert rc.stats_snapshot()["entries"] == 0

    def test_reset_clears_tenant_quotas(self):
        rc = self._cache(tenant_default_bytes=8)
        rc.reset()
        rc.configure(budget_bytes=1 << 20)
        rc.put(("k", "q", (0,), False), "count", "i", "q", 5, _vec(1))
        assert rc.stats_snapshot()["entries"] == 1

    def test_drop_index_gcs_quota_eviction_ledger(self):
        rc = self._cache(tenant_overrides={"idx_a": 1})
        # quota 1 byte: every put rejected, so force the ledger via a
        # default small enough to store then shrink
        rc.configure(tenant_overrides={})
        rc.put(("a", "q", (0,), False), "count", "idx_a", "q", 1, _vec(1))
        nb = rc.stats_snapshot()["by_index"]["idx_a"]
        rc.put(("a2", "q2", (0,), False), "count", "idx_a", "q2", 2, _vec(2))
        rc.configure(tenant_overrides={"idx_a": nb})  # now over: evicts
        assert rc.stats_snapshot()["quota_evictions_by_index"].get(
            "idx_a", 0
        ) >= 1
        rc.drop_index("idx_a")
        assert rc.stats_snapshot()["quota_evictions_by_index"] == {}


# ---------------------------------------------------------------------------
# server integration: 429 detail headers, tenant gauges, overview
# ---------------------------------------------------------------------------


def _post_query(uri, index, pql, headers=None):
    req = urllib.request.Request(
        f"{uri}/index/{index}/query",
        data=json.dumps({"query": pql}).encode(),
        method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        return resp.status, json.loads(resp.read())


def _seed(api, index, rows=(1,), n=50):
    api.create_index(index)
    api.create_field(index, "f", {"type": "set"})
    for r in rows:
        api.import_bits(
            index, "f",
            np.full(n, r, np.uint64),
            np.arange(n, dtype=np.uint64),
        )


def test_quota_shed_carries_429_detail_headers():
    with ClusterHarness(
        1,
        in_memory=True,
        telemetry_sample_interval=0.0,
        shed_retry_after=0.5,
        tenant_overrides=["abuser:qps=1"],
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        _seed(srv.api, "abuser")
        _seed(srv.api, "good")
        status, _ = _post_query(uri, "abuser", "Count(Row(f=1))")
        assert status == 200  # the one-second burst token
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_query(uri, "abuser", "Count(Row(f=1))")
        e = ei.value
        assert e.code == 429
        assert e.headers.get("X-Pilosa-Quota-Limit") == "qps"
        assert float(e.headers.get("X-Pilosa-Quota-Value")) == 1.0
        # derived bucket refill (~1s) dominates the 0.5 floor
        assert float(e.headers.get("X-Pilosa-Retry-After")) >= 0.5
        assert int(e.headers.get("Retry-After")) >= 1
        e.close()
        # the unlimited tenant is untouched by its neighbor's limit
        status, body = _post_query(uri, "good", "Count(Row(f=1))")
        assert status == 200 and body["results"] == [50]
        # node-saturation sheds keep the taxonomy but carry NO quota
        # headers (nothing tenant-specific tripped)
        snap = srv.stats.registry.snapshot()
        assert any(
            "sched.shed" in k and "reason:rate" in k
            and "index:abuser" in k
            for k in snap
        ), sorted(k for k in snap if "shed" in k)


def test_tenant_gauges_publish_only_when_configured():
    with ClusterHarness(
        1, in_memory=True, telemetry_sample_interval=0.0
    ) as c:
        srv = c[0]
        _seed(srv.api, "quiet")
        srv.publish_cache_gauges()
        assert not any(
            k.startswith("tenant.")
            for k in srv.stats.registry.snapshot()
        )
    with ClusterHarness(
        1,
        in_memory=True,
        telemetry_sample_interval=0.0,
        tenant_default_hbm_bytes=1 << 30,
        tenant_overrides=["t0:cache-bytes=4096"],
    ) as c:
        srv = c[0]
        _seed(srv.api, "t0")
        srv.publish_cache_gauges()
        snap = srv.stats.registry.snapshot()
        assert snap.get("tenant.hbm_quota_bytes;index:t0") == 1 << 30
        assert snap.get("tenant.cache_quota_bytes;index:t0") == 4096
        assert snap.get("tenant.inflight_quota_bytes;index:t0") == 0
        # overview rows carry the quota column
        overview = srv.telemetry.cluster_overview()
        row = overview["indexes"]["t0"]
        assert row["quotaBytes"] == 1 << 30
        assert row["quotaEvictions"] >= 0


# ---------------------------------------------------------------------------
# overload soak (@slow): one abusive tenant among well-behaved ones
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_two_tenant_overload_soak():
    """N tenants, one abusive (tight query loop, no backoff) with a qps
    and an HBM quota; the rest issue modest repeat Counts. Acceptance:
    the abusive index sheds 429 + informed Retry-After + quota headers;
    the well-behaved tenants see NO sheds, bounded latency, and keep
    their result-cache residency; quota-first eviction pressure lands
    only on the abusive index's devcache attribution."""
    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.core.resultcache import RESULT_CACHE

    good = [f"soak_t{i}" for i in range(4)]
    with ClusterHarness(
        1,
        in_memory=True,
        telemetry_sample_interval=0.0,
        max_concurrent_queries=4,
        admission_queue_depth=16,
        shed_retry_after=0.1,
        tenant_overrides=["soak_abuser:qps=5;hbm-bytes=65536"],
    ) as c:
        srv = c[0]
        uri = srv.node.uri
        for idx in good:
            _seed(srv.api, idx)
        _seed(srv.api, "soak_abuser", rows=(1, 2, 3))
        stop = time.monotonic() + 3.0
        results = {idx: {"ok": 0, "shed": 0, "lat": []} for idx in good}
        results["soak_abuser"] = {"ok": 0, "shed": 0, "lat": []}
        headers_seen = []
        hlock = threading.Lock()

        def tenant_loop(idx, pqls, pause):
            i = 0
            while time.monotonic() < stop:
                t0 = time.monotonic()
                try:
                    status, _ = _post_query(uri, idx, pqls[i % len(pqls)])
                    results[idx]["ok"] += 1
                    results[idx]["lat"].append(time.monotonic() - t0)
                except urllib.error.HTTPError as e:
                    results[idx]["shed"] += 1
                    if e.code == 429:
                        with hlock:
                            headers_seen.append(
                                (
                                    idx,
                                    e.headers.get("X-Pilosa-Quota-Limit"),
                                    e.headers.get("Retry-After"),
                                )
                            )
                    e.close()
                i += 1
                if pause:
                    time.sleep(pause)

        threads = [
            threading.Thread(
                target=tenant_loop,
                args=(idx, ["Count(Row(f=1))"], 0.03),
                daemon=True,
            )
            for idx in good
        ] + [
            threading.Thread(
                target=tenant_loop,
                args=(
                    "soak_abuser",
                    ["Row(f=1)", "Row(f=2)", "Row(f=3)"],
                    0.0,
                ),
                daemon=True,
            )
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)

        # the abusive tenant was actually shed, with informed detail
        ab = results["soak_abuser"]
        assert ab["shed"] > 0, results
        assert ab["ok"] <= 5 * 3.0 + 6  # rate-limited to ~qps * wall
        quota_sheds = [h for h in headers_seen if h[0] == "soak_abuser"]
        assert quota_sheds and all(
            lim == "qps" and int(ra) >= 1 for _, lim, ra in quota_sheds
        ), quota_sheds[:5]
        # well-behaved tenants: zero sheds, every query answered, tail
        # latency bounded (generous: CI boxes are noisy)
        for idx in good:
            r = results[idx]
            assert r["shed"] == 0, (idx, r)
            assert r["ok"] > 0, (idx, r)
            lat = sorted(r["lat"])
            p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
            assert p99 < 5.0, (idx, p99)
        # quota-first eviction pressure landed ONLY on the abuser: its
        # three distinct row operands cannot fit a 64 KiB quota
        qev = DEVICE_CACHE.quota_evictions_by_index()
        assert qev.get("soak_abuser", 0) > 0, qev
        assert set(qev) <= {"soak_abuser"}, qev
        # the good tenants' cached repeats survived the abuse
        by_index = RESULT_CACHE.stats_snapshot()["by_index"]
        for idx in good:
            assert by_index.get(idx, 0) > 0, by_index
        # shed taxonomy on /metrics: the abuser's rate sheds are tagged
        snap = srv.stats.registry.snapshot()
        assert any(
            "sched.shed" in k
            and "index:soak_abuser" in k
            and "reason:rate" in k
            for k in snap
        )
