"""CLI + config tests (reference model: cmd/*_test.go, ctl/*_test.go)."""

import io
import json
import sys
import urllib.request

import pytest

from pilosa_tpu.cli.config import Config, parse_hosts
from pilosa_tpu.cli.main import cmd_check, main


# ---------------------------------------------------------------------------
# config precedence (cmd/root.go:94 setAllConfig)
# ---------------------------------------------------------------------------


def test_config_defaults():
    cfg = Config()
    assert cfg.bind == "localhost:10101"
    assert cfg.cluster.replicas == 1


def test_config_toml_env_flag_precedence(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text(
        'bind = "localhost:7001"\nverbose = true\n'
        "[cluster]\nreplicas = 3\n"
        'hosts = ["n0@http://a:1", "n1@http://b:2"]\n'
    )
    cfg = Config.load(str(p), env={})
    assert cfg.bind == "localhost:7001"
    assert cfg.cluster.replicas == 3
    assert cfg.verbose is True

    # env overrides toml
    cfg = Config.load(str(p), env={"PILOSA_TPU_BIND": "localhost:7002",
                                   "PILOSA_TPU_CLUSTER__REPLICAS": "2"})
    assert cfg.bind == "localhost:7002"
    assert cfg.cluster.replicas == 2

    # explicit overrides beat env
    cfg = Config.load(
        str(p),
        env={"PILOSA_TPU_BIND": "localhost:7002"},
        overrides={"bind": "localhost:7003"},
    )
    assert cfg.bind == "localhost:7003"


def test_config_toml_roundtrip():
    cfg = Config()
    cfg.cluster.hosts = ["n0@http://a:1"]
    dumped = cfg.to_toml()
    try:
        import tomllib
    except ImportError:
        import tomli as tomllib

    parsed = tomllib.loads(dumped)
    assert parsed["bind"] == cfg.bind
    assert parsed["cluster"]["hosts"] == ["n0@http://a:1"]
    assert parsed["anti-entropy"]["interval"] == 0.0


def test_parse_hosts():
    assert parse_hosts(["n0@http://a:1", "b:2"]) == [
        ("n0", "http://a:1"),
        ("b-2", "http://b:2"),
    ]


def test_generate_config_command(capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    assert 'bind = "localhost:10101"' in out


# ---------------------------------------------------------------------------
# import/export/check against a live in-process server
# ---------------------------------------------------------------------------


def test_import_export_roundtrip(tmp_path, monkeypatch, capsys):
    from pilosa_tpu.testing import ClusterHarness

    csv = tmp_path / "bits.csv"
    csv.write_text("".join(f"{i % 3},{i * 7}\n" for i in range(100)))

    with ClusterHarness(1, in_memory=True) as c:
        host = c[0].node.uri
        assert (
            main(
                [
                    "import", "--host", host, "-i", "imp", "-f", "f",
                    "--create", str(csv),
                ]
            )
            == 0
        )
        (cnt,) = c[0].api.query("imp", "Count(Row(f=0))")
        assert cnt == 34

        assert main(["export", "--host", host, "-i", "imp", "-f", "f"]) == 0
        out_lines = [
            l for l in capsys.readouterr().out.splitlines() if l.strip()
        ]
        assert len(out_lines) == 100
        assert out_lines[0].split(",") == ["0", "0"]


def test_import_int_field(tmp_path):
    from pilosa_tpu.testing import ClusterHarness

    csv = tmp_path / "vals.csv"
    csv.write_text("100,1\n250,2\n37,3\n")
    with ClusterHarness(1, in_memory=True) as c:
        host = c[0].node.uri
        assert (
            main(
                [
                    "import", "--host", host, "-i", "vals", "-f", "amt",
                    "--create", "--field-type", "int", str(csv),
                ]
            )
            == 0
        )
        (vc,) = c[0].api.query("vals", "Sum(field=amt)")
        assert (vc.value, vc.count) == (387, 3)


def test_inspect_and_check(tmp_path, capsys):
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.exec.executor import Executor

    d = str(tmp_path / "data")
    h = Holder(d).open()
    h.create_index("i").create_field("f", FieldOptions())
    e = Executor(h)
    e.execute("i", "Set(1, f=2) Set(9, f=2)")
    h.close()

    assert main(["inspect", d]) == 0
    out = capsys.readouterr().out
    assert "i/f/standard/shard=0" in out and "bits=2" in out

    assert cmd_check([d]) == 0
    out = capsys.readouterr().out
    assert "ok" in out

    # corrupt a wal file -> check fails
    import glob

    wals = glob.glob(f"{d}/**/*.wal", recursive=True)
    assert wals
    # benign torn tail (crash mid-append) -> still ok
    with open(wals[0], "ab") as f:
        f.write(b"\x4c\x57")  # partial header
    assert cmd_check([wals[0]]) == 0
    out = capsys.readouterr().out
    assert "partial header" in out
    # real corruption (bad magic mid-file) -> fails
    with open(wals[0], "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")
    assert cmd_check([wals[0]]) == 1


def test_server_command_boots(tmp_path):
    from pilosa_tpu.cli.main import cmd_server

    cfg = Config()
    cfg.data_dir = str(tmp_path / "node")
    cfg.bind = "localhost:0"
    srv = cmd_server(cfg, wait=False)
    try:
        with urllib.request.urlopen(f"{srv.node.uri}/status", timeout=5) as r:
            status = json.loads(r.read())
        assert status["state"] == "NORMAL"
    finally:
        srv.stop()


def test_server_command_boots_tls(tmp_path):
    """The CLI-level TLS wiring: flag parsing -> Config.tls -> cmd_server
    -> an HTTPS-serving node whose advertised URI matches the scheme."""
    import shutil
    import ssl
    import subprocess

    import pytest

    if shutil.which("openssl") is None:
        pytest.skip("openssl binary not available for cert generation")

    from pilosa_tpu.cli.main import _build_parser, _load_config, cmd_server

    cert, key = str(tmp_path / "c.crt"), str(tmp_path / "c.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "2", "-nodes",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    args = _build_parser().parse_args(
        [
            "server", "--data-dir", str(tmp_path / "node"),
            "--bind", "localhost:0",
            "--tls-certificate", cert, "--tls-key", key,
            "--tls-skip-verify",
        ]
    )
    cfg = _load_config(args)
    assert cfg.tls.certificate == cert and cfg.tls.skip_verify
    srv = cmd_server(cfg, wait=False)
    try:
        assert srv.node.uri.startswith("https://")
        ctx = ssl.create_default_context(cafile=cert)
        with urllib.request.urlopen(
            f"{srv.node.uri}/status", timeout=5, context=ctx
        ) as r:
            assert json.loads(r.read())["state"] == "NORMAL"
    finally:
        srv.stop()
