"""Tests for the tracked-lock runtime deadlock detector (utils/locks.py).

The detector itself must be trustworthy before the whole suite leans on
it (conftest fails any test that records a violation): these tests
construct real AB/BA orderings on two threads and assert the cycle
report names both acquisition sites, that reentrancy/ordered nesting
stay clean, and that disabled-mode factories are passthrough-cheap.
"""

import threading
import time

import pytest

from pilosa_tpu.utils import locks


@pytest.fixture(autouse=True)
def _isolated_graph():
    """Each test here runs on a fresh order graph (these tests seed
    deliberate violations), but the suite-wide graph accumulated by the
    other test modules is snapshotted and restored — wiping it would
    blind conftest's cross-test AB/BA detection for everything collected
    after this file."""
    state = locks._state
    with state.mu:
        saved = (
            dict(state.edges),
            {k: set(v) for k, v in state.adj.items()},
            list(state.violations),
            list(state.warnings),
        )
    locks.reset()
    yield
    with state.mu:
        state.edges, state.adj = dict(saved[0]), {
            k: set(v) for k, v in saved[1].items()
        }
        state.violations[:] = saved[2]
        state.warnings[:] = saved[3]


def _run_threads(*fns):
    threads = [threading.Thread(target=fn) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class TestCycleDetection:
    def test_ab_ba_cycle_on_two_threads_reports_both_sites(self):
        a = locks.TrackedLock("test.A")
        b = locks.TrackedLock("test.B")
        barrier = threading.Event()

        def t1():
            with a:
                with b:  # A -> B
                    pass
            barrier.set()

        def t2():
            barrier.wait(5)
            with b:
                with a:  # B -> A: closes the cycle
                    pass

        _run_threads(t1, t2)
        vs = locks.violations()
        assert len(vs) == 1
        v = vs[0]
        assert v.kind == "cycle"
        assert "test.A" in v.message and "test.B" in v.message
        # both acquisition stacks captured, each naming this file
        assert "test_locks.py" in v.stack_a
        assert "test_locks.py" in v.stack_b
        assert "in t1" in v.stack_a
        assert "in t2" in v.stack_b

    def test_consistent_ordering_is_clean(self):
        a = locks.TrackedLock("test.A")
        b = locks.TrackedLock("test.B")

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        _run_threads(worker, worker)
        assert locks.violations() == []

    def test_three_lock_transitive_cycle(self):
        a = locks.TrackedLock("test.A")
        b = locks.TrackedLock("test.B")
        c = locks.TrackedLock("test.C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:  # A -> B -> C -> A
                pass
        vs = locks.violations()
        assert len(vs) == 1
        assert vs[0].kind == "cycle"
        assert "test.A" in vs[0].message and "test.C" in vs[0].message

    def test_same_class_nesting_across_instances_flagged(self):
        # two *instances* of the same lock class nested: unordered
        # same-class nesting is the classic transfer() deadlock
        a1 = locks.TrackedLock("test.same")
        a2 = locks.TrackedLock("test.same")
        with a1:
            with a2:
                pass
        vs = locks.violations()
        assert len(vs) == 1
        assert vs[0].kind == "cycle"


class TestSelfDeadlock:
    def test_nonreentrant_reacquire_flagged(self):
        a = locks.TrackedLock("test.self")
        a.acquire()
        try:
            got = a.acquire(blocking=False)
            assert got is False
        finally:
            a.release()
        vs = locks.violations()
        assert len(vs) == 1
        assert vs[0].kind == "self-deadlock"
        assert "test.self" in vs[0].message

    def test_rlock_reentrancy_clean(self):
        r = locks.TrackedRLock("test.rlock")
        with r:
            with r:
                with r:
                    pass
        assert locks.violations() == []


class TestCondition:
    def test_wait_notify_roundtrip(self):
        cond = locks.TrackedCondition(name="test.cv")
        state = {"go": False}
        hits = []

        def waiter():
            with cond:
                ok = cond.wait_for(lambda: state["go"], timeout=5)
                hits.append(ok)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            state["go"] = True
            cond.notify_all()
        t.join(5)
        assert hits == [True]
        assert locks.violations() == []


class TestPassthrough:
    def test_disabled_factories_return_raw_primitives(self):
        assert locks.checking_enabled()  # conftest turned it on
        locks.disable_checking()
        try:
            raw = locks.TrackedLock("p")
            rawr = locks.TrackedRLock("p")
            assert isinstance(raw, type(threading.Lock()))
            assert isinstance(rawr, type(threading.RLock()))
        finally:
            locks.enable_checking()

    def test_disabled_factories_add_no_measurable_overhead(self):
        """Passthrough-cheap: the disabled factory hands back the raw
        primitive, so acquire/release cost is identical by construction;
        assert the uncontended loop stays within a loose factor of raw
        (same object type, so this is really a guard against the factory
        accidentally returning a wrapper)."""
        locks.disable_checking()
        try:
            tracked = locks.TrackedLock("perf")
        finally:
            locks.enable_checking()
        raw = threading.Lock()
        n = 20_000

        def loop(lk):
            t0 = time.perf_counter()
            for _ in range(n):
                with lk:
                    pass
            return time.perf_counter() - t0

        loop(raw), loop(tracked)  # warm
        t_raw, t_tracked = loop(raw), loop(tracked)
        assert type(tracked) is type(raw)
        assert t_tracked < t_raw * 3 + 0.05

    def test_enabled_wrapper_supports_lock_api(self):
        lk = locks.TrackedLock("test.api")
        assert lk.acquire(timeout=1)
        assert lk.locked()
        lk.release()
        assert not lk.locked()


class TestHoldThreshold:
    def test_long_hold_recorded_as_warning(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_LOCK_HOLD_MS", "1")
        lk = locks.TrackedLock("test.hold")
        with lk:
            time.sleep(0.01)
        ws = locks.warnings()
        assert len(ws) == 1
        assert ws[0].kind == "long-hold"
        assert "test.hold" in ws[0].message
        # warnings never fail the suite: violations stay empty
        assert locks.violations() == []

    def test_fast_hold_not_flagged(self, monkeypatch):
        monkeypatch.setenv("PILOSA_TPU_LOCK_HOLD_MS", "5000")
        lk = locks.TrackedLock("test.hold2")
        with lk:
            pass
        assert locks.warnings() == []


class TestReportFormat:
    def test_format_report_clean(self):
        assert locks.format_report() == "lock check: clean"

    def test_format_report_renders_violations(self):
        a = locks.TrackedLock("test.RA")
        b = locks.TrackedLock("test.RB")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        rep = locks.format_report()
        assert "[cycle]" in rep
        assert "test.RA" in rep and "test.RB" in rep
        assert "first site" in rep and "second site" in rep
