"""Cluster layer tests: placement math, resize diffing, anti-entropy merge.

Reference test model: cluster_internal_test.go (partition/hasher/fragSources
math) and fragment tests around mergeBlock.
"""

import numpy as np
import pytest

from pilosa_tpu.cluster import (
    Cluster,
    Frag,
    JumpHasher,
    ModHasher,
    Node,
    block_checksums,
    diff_blocks,
    merge_block,
)
from pilosa_tpu.cluster.topology import (
    RESIZE_ADD,
    RESIZE_REMOVE,
    STATE_DEGRADED,
    STATE_DOWN,
    STATE_NORMAL,
    ClusterError,
    fnv1a64,
)


def make_cluster(n, replica_n=1, hasher=None):
    return Cluster(
        nodes=[Node(id=f"node{i}", uri=f"http://host{i}:10101") for i in range(n)],
        replica_n=replica_n,
        hasher=hasher or JumpHasher(),
    )


# ---------------------------------------------------------------------------
# hashing / placement
# ---------------------------------------------------------------------------


def test_fnv1a64_known_vectors():
    # standard FNV-1a test vectors
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_jump_hasher_properties():
    h = JumpHasher()
    # deterministic, in range
    for key in range(200):
        for n in (1, 2, 3, 8, 64):
            b = h.hash(key, n)
            assert 0 <= b < n
            assert b == h.hash(key, n)
    # minimal movement: growing n moves keys only INTO the new bucket
    for key in range(500):
        b7, b8 = h.hash(key, 7), h.hash(key, 8)
        assert b7 == b8 or b8 == 7


def test_jump_hasher_balance():
    h = JumpHasher()
    counts = [0] * 8
    for key in range(4096):
        counts[h.hash(key, 8)] += 1
    for c in counts:
        assert 300 < c < 730  # roughly uniform


def test_partition_determinism_and_spread():
    c = make_cluster(4)
    parts = {c.partition("idx", s) for s in range(1000)}
    assert len(parts) > 200  # spreads over the 256 partitions
    assert c.partition("idx", 5) == c.partition("idx", 5)
    assert c.partition("idx", 5) != c.partition("other", 5) or True  # index-dependent


def test_shard_nodes_replication():
    c = make_cluster(5, replica_n=3)
    owners = c.shard_nodes("i", 42)
    assert len(owners) == 3
    assert len({n.id for n in owners}) == 3
    # consecutive on the ring
    ids = [n.id for n in c.nodes]
    start = ids.index(owners[0].id)
    assert [n.id for n in owners] == [ids[(start + i) % 5] for i in range(3)]


def test_replica_n_clamped_to_node_count():
    c = make_cluster(2, replica_n=5)
    assert len(c.shard_nodes("i", 0)) == 2


def test_owns_shard_and_contains_shards():
    c = make_cluster(3, replica_n=2)
    shards = list(range(50))
    total = 0
    for node in c.nodes:
        owned = c.contains_shards("i", shards, node.id)
        total += len(owned)
        for s in owned:
            assert c.owns_shard(node.id, "i", s)
    assert total == 50 * 2  # every shard placed on exactly replica_n nodes


def test_shards_by_node_covers_all_shards():
    c = make_cluster(4, replica_n=2)
    shards = list(range(64))
    grouping = c.shards_by_node("i", shards)
    got = sorted(s for ss in grouping.values() for s in ss)
    assert got == shards


def test_shards_by_node_skips_down_nodes():
    c = make_cluster(3, replica_n=2)
    c.nodes[0].state = "DOWN"
    grouping = c.shards_by_node("i", list(range(64)))
    assert c.nodes[0].id not in grouping
    got = sorted(s for ss in grouping.values() for s in ss)
    assert got == list(range(64))  # replicas absorb the down node's shards


# ---------------------------------------------------------------------------
# resize math
# ---------------------------------------------------------------------------


def test_diff_add_and_remove():
    c3 = make_cluster(3)
    c4 = c3.with_added_node(Node(id="node3"))
    assert c3.diff(c4) == (RESIZE_ADD, "node3")
    assert c4.diff(c3) == (RESIZE_REMOVE, "node3")
    with pytest.raises(ClusterError):
        c3.diff(c3.with_added_node(Node(id="x")).with_added_node(Node(id="y")))


def frags_for(shards, field="f", view="standard"):
    return [Frag(field=field, view=view, shard=s) for s in shards]


def test_frag_sources_add_node():
    old = make_cluster(3, replica_n=1)
    new = old.with_added_node(Node(id="node3"))
    frags = frags_for(range(40))
    sources = old.frag_sources(new, "i", frags)
    # the new node must fetch exactly what it now owns
    new_owned = {fr for fr in frags if new.owns_shard("node3", "i", fr.shard)}
    fetched = {
        Frag(field=s.field, view=s.view, shard=s.shard) for s in sources["node3"]
    }
    assert fetched == new_owned
    # every source node actually held the fragment in the old cluster
    for node_id, srcs in sources.items():
        for s in srcs:
            assert old.owns_shard(s.node.id, "i", s.shard)
    # existing nodes with unchanged placement fetch nothing extra they had
    for node_id, srcs in sources.items():
        for s in srcs:
            assert not old.owns_shard(node_id, "i", s.shard)


def test_frag_sources_remove_node_requires_replica():
    old = make_cluster(3, replica_n=1)
    new = old.with_removed_node("node2")
    frags = frags_for(range(40))
    owned_by_2 = [fr for fr in frags if old.owns_shard("node2", "i", fr.shard)]
    if owned_by_2:  # with replica 1, removing a data-holding node must fail
        with pytest.raises(ClusterError):
            old.frag_sources(new, "i", frags)


def test_frag_sources_remove_node_with_replicas():
    old = make_cluster(3, replica_n=2)
    new = old.with_removed_node("node2")
    frags = frags_for(range(40))
    sources = old.frag_sources(new, "i", frags)
    for node_id, srcs in sources.items():
        for s in srcs:
            assert s.node.id != "node2"  # departing node is never a source
            assert old.owns_shard(s.node.id, "i", s.shard)
    # after resize every fragment is fully replicated on the new cluster
    for fr in frags:
        owners = {n.id for n in new.shard_nodes("i", fr.shard)}
        for node_id in owners:
            had = old.owns_shard(node_id, "i", fr.shard)
            gets = any(
                s.shard == fr.shard for s in sources.get(node_id, [])
            )
            assert had or gets


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_determine_state():
    c = make_cluster(4, replica_n=2)
    assert c.determine_state(set()) == STATE_NORMAL
    assert c.determine_state({"node1"}) == STATE_DEGRADED
    assert c.determine_state({"node1", "node2"}) == STATE_DOWN


# ---------------------------------------------------------------------------
# anti-entropy
# ---------------------------------------------------------------------------


def P(pairs):
    if not pairs:
        return np.empty(0, np.uint64), np.empty(0, np.uint64)
    r, c = zip(*pairs)
    return np.array(r, np.uint64), np.array(c, np.uint64)


def test_block_checksums_detect_difference():
    a = block_checksums(P([(0, 1), (0, 5), (150, 7)]))
    b = block_checksums(P([(0, 1), (0, 5), (150, 8)]))
    assert set(a) == {0, 1}
    assert a[0] == b[0]
    assert a[1] != b[1]
    assert diff_blocks(a, b) == [1]


def test_block_checksums_empty():
    assert block_checksums(P([])) == {}


def test_merge_block_two_replicas_union():
    # even split -> set wins (fragment.go:1917)
    a = P([(0, 1), (0, 2)])
    b = P([(0, 2), (0, 3)])
    sets, clears = merge_block(0, [a, b])
    # replica a must add (0,3); replica b must add (0,1); no clears
    assert [(int(r), int(c)) for r, c in zip(*sets[0])] == [(0, 3)]
    assert [(int(r), int(c)) for r, c in zip(*sets[1])] == [(0, 1)]
    assert all(len(r) == 0 for r, _ in clears)


def test_merge_block_three_replicas_majority():
    a = P([(0, 1), (0, 9)])
    b = P([(0, 1)])
    c = P([(0, 2)])
    sets, clears = merge_block(0, [a, b, c])
    # (0,1): 2/3 votes -> kept; c must set it
    assert (0, 1) in [(int(r), int(cc)) for r, cc in zip(*sets[2])]
    # (0,9) and (0,2): 1/3 votes -> cleared from their holders
    assert (0, 9) in [(int(r), int(cc)) for r, cc in zip(*clears[0])]
    assert (0, 2) in [(int(r), int(cc)) for r, cc in zip(*clears[2])]
    # b only needs nothing cleared
    assert len(clears[1][0]) == 0


def test_merge_block_ignores_out_of_block_pairs():
    a = P([(0, 1), (250, 2)])  # row 250 is in block 2
    b = P([])
    sets, clears = merge_block(0, [a, b])
    got = [(int(r), int(c)) for r, c in zip(*sets[1])]
    assert got == [(0, 1)]


def test_merge_convergence_end_to_end():
    rng = np.random.default_rng(3)
    replicas = []
    for _ in range(3):
        n = rng.integers(50, 150)
        rows = rng.integers(0, 100, n).astype(np.uint64)
        cols = rng.integers(0, 1000, n).astype(np.uint64)
        replicas.append((rows, cols))
    sets, clears = merge_block(0, replicas)

    def apply(rep, s, cl):
        have = {(int(r), int(c)) for r, c in zip(*rep)}
        have |= {(int(r), int(c)) for r, c in zip(*s)}
        have -= {(int(r), int(c)) for r, c in zip(*cl)}
        return have

    states = [apply(rep, s, cl) for rep, s, cl in zip(replicas, sets, clears)]
    assert states[0] == states[1] == states[2]


# ---------------------------------------------------------------------------
# fragment integration
# ---------------------------------------------------------------------------


def test_fragment_block_sync_roundtrip():
    from pilosa_tpu.core.fragment import Fragment

    fa = Fragment(None, "i", "f", "standard", 0).open()
    fb = Fragment(None, "i", "f", "standard", 0).open()
    fa.bulk_import(np.array([0, 0, 1, 205]), np.array([3, 4, 9, 11]))
    fb.bulk_import(np.array([0, 1, 205]), np.array([3, 9, 12]))

    diffs = diff_blocks(fa.block_checksums(), fb.block_checksums())
    assert diffs == [0, 2]
    for bid in diffs:
        sets, clears = merge_block(bid, [fa.block_pairs(bid), fb.block_pairs(bid)])
        fa.apply_deltas(sets[0], clears[0])
        fb.apply_deltas(sets[1], clears[1])
    assert diff_blocks(fa.block_checksums(), fb.block_checksums()) == []
    assert fa.pairs()[1].tolist() == fb.pairs()[1].tolist()


def test_fragment_stream_roundtrip(tmp_path):
    from pilosa_tpu.core.fragment import Fragment

    src = Fragment(None, "i", "f", "standard", 3).open()
    src.bulk_import(np.array([0, 5, 7]), np.array([10, 20, 30]))
    blob = src.to_bytes()

    dst = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 3).open()
    dst.from_bytes(blob)
    assert dst.pairs()[0].tolist() == src.pairs()[0].tolist()
    assert dst.pairs()[1].tolist() == src.pairs()[1].tolist()
    # persisted: reopen from disk
    dst.close()
    dst2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 3).open()
    assert dst2.pairs()[1].tolist() == src.pairs()[1].tolist()


def test_fragment_stream_rejects_wrong_shard():
    from pilosa_tpu.core.fragment import Fragment

    src = Fragment(None, "i", "f", "standard", 3).open()
    src.bulk_import(np.array([0]), np.array([10]))
    dst = Fragment(None, "i", "f", "standard", 5).open()
    with pytest.raises(ValueError):
        dst.from_bytes(src.to_bytes())


def test_mod_hasher():
    c = make_cluster(3, hasher=ModHasher())
    assert [c.hasher.hash(k, 3) for k in range(6)] == [0, 1, 2, 0, 1, 2]
