"""Cluster layer tests: placement math, resize diffing, anti-entropy merge.

Reference test model: cluster_internal_test.go (partition/hasher/fragSources
math) and fragment tests around mergeBlock.
"""

import numpy as np
import pytest

from pilosa_tpu.cluster import (
    Cluster,
    Frag,
    JumpHasher,
    ModHasher,
    Node,
    block_checksums,
    diff_blocks,
    merge_block,
)
from pilosa_tpu.cluster.topology import (
    RESIZE_ADD,
    RESIZE_REMOVE,
    STATE_DEGRADED,
    STATE_DOWN,
    STATE_NORMAL,
    ClusterError,
    fnv1a64,
)


def make_cluster(n, replica_n=1, hasher=None):
    return Cluster(
        nodes=[Node(id=f"node{i}", uri=f"http://host{i}:10101") for i in range(n)],
        replica_n=replica_n,
        hasher=hasher or JumpHasher(),
    )


# ---------------------------------------------------------------------------
# hashing / placement
# ---------------------------------------------------------------------------


def test_fnv1a64_known_vectors():
    # standard FNV-1a test vectors
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_jump_hasher_properties():
    h = JumpHasher()
    # deterministic, in range
    for key in range(200):
        for n in (1, 2, 3, 8, 64):
            b = h.hash(key, n)
            assert 0 <= b < n
            assert b == h.hash(key, n)
    # minimal movement: growing n moves keys only INTO the new bucket
    for key in range(500):
        b7, b8 = h.hash(key, 7), h.hash(key, 8)
        assert b7 == b8 or b8 == 7


def test_jump_hasher_balance():
    h = JumpHasher()
    counts = [0] * 8
    for key in range(4096):
        counts[h.hash(key, 8)] += 1
    for c in counts:
        assert 300 < c < 730  # roughly uniform


def test_partition_determinism_and_spread():
    c = make_cluster(4)
    parts = {c.partition("idx", s) for s in range(1000)}
    assert len(parts) > 200  # spreads over the 256 partitions
    assert c.partition("idx", 5) == c.partition("idx", 5)
    assert c.partition("idx", 5) != c.partition("other", 5) or True  # index-dependent


def test_shard_nodes_replication():
    c = make_cluster(5, replica_n=3)
    owners = c.shard_nodes("i", 42)
    assert len(owners) == 3
    assert len({n.id for n in owners}) == 3
    # consecutive on the ring
    ids = [n.id for n in c.nodes]
    start = ids.index(owners[0].id)
    assert [n.id for n in owners] == [ids[(start + i) % 5] for i in range(3)]


def test_replica_n_clamped_to_node_count():
    c = make_cluster(2, replica_n=5)
    assert len(c.shard_nodes("i", 0)) == 2


def test_owns_shard_and_contains_shards():
    c = make_cluster(3, replica_n=2)
    shards = list(range(50))
    total = 0
    for node in c.nodes:
        owned = c.contains_shards("i", shards, node.id)
        total += len(owned)
        for s in owned:
            assert c.owns_shard(node.id, "i", s)
    assert total == 50 * 2  # every shard placed on exactly replica_n nodes


def test_shards_by_node_covers_all_shards():
    c = make_cluster(4, replica_n=2)
    shards = list(range(64))
    grouping = c.shards_by_node("i", shards)
    got = sorted(s for ss in grouping.values() for s in ss)
    assert got == shards


def test_shards_by_node_skips_down_nodes():
    c = make_cluster(3, replica_n=2)
    c.nodes[0].state = "DOWN"
    grouping = c.shards_by_node("i", list(range(64)))
    assert c.nodes[0].id not in grouping
    got = sorted(s for ss in grouping.values() for s in ss)
    assert got == list(range(64))  # replicas absorb the down node's shards


# ---------------------------------------------------------------------------
# resize math
# ---------------------------------------------------------------------------


def test_diff_add_and_remove():
    c3 = make_cluster(3)
    c4 = c3.with_added_node(Node(id="node3"))
    assert c3.diff(c4) == (RESIZE_ADD, "node3")
    assert c4.diff(c3) == (RESIZE_REMOVE, "node3")
    with pytest.raises(ClusterError):
        c3.diff(c3.with_added_node(Node(id="x")).with_added_node(Node(id="y")))


def frags_for(shards, field="f", view="standard"):
    return [Frag(field=field, view=view, shard=s) for s in shards]


def test_frag_sources_add_node():
    old = make_cluster(3, replica_n=1)
    new = old.with_added_node(Node(id="node3"))
    frags = frags_for(range(40))
    sources = old.frag_sources(new, "i", frags)
    # the new node must fetch exactly what it now owns
    new_owned = {fr for fr in frags if new.owns_shard("node3", "i", fr.shard)}
    fetched = {
        Frag(field=s.field, view=s.view, shard=s.shard) for s in sources["node3"]
    }
    assert fetched == new_owned
    # every source node actually held the fragment in the old cluster
    for node_id, srcs in sources.items():
        for s in srcs:
            assert old.owns_shard(s.node.id, "i", s.shard)
    # existing nodes with unchanged placement fetch nothing extra they had
    for node_id, srcs in sources.items():
        for s in srcs:
            assert not old.owns_shard(node_id, "i", s.shard)


def test_frag_sources_remove_node_requires_replica():
    old = make_cluster(3, replica_n=1)
    new = old.with_removed_node("node2")
    frags = frags_for(range(40))
    owned_by_2 = [fr for fr in frags if old.owns_shard("node2", "i", fr.shard)]
    if owned_by_2:  # with replica 1, removing a data-holding node must fail
        with pytest.raises(ClusterError):
            old.frag_sources(new, "i", frags)


def test_frag_sources_remove_node_with_replicas():
    old = make_cluster(3, replica_n=2)
    new = old.with_removed_node("node2")
    frags = frags_for(range(40))
    sources = old.frag_sources(new, "i", frags)
    for node_id, srcs in sources.items():
        for s in srcs:
            assert s.node.id != "node2"  # departing node is never a source
            assert old.owns_shard(s.node.id, "i", s.shard)
    # after resize every fragment is fully replicated on the new cluster
    for fr in frags:
        owners = {n.id for n in new.shard_nodes("i", fr.shard)}
        for node_id in owners:
            had = old.owns_shard(node_id, "i", fr.shard)
            gets = any(
                s.shard == fr.shard for s in sources.get(node_id, [])
            )
            assert had or gets


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_determine_state():
    c = make_cluster(4, replica_n=2)
    assert c.determine_state(set()) == STATE_NORMAL
    assert c.determine_state({"node1"}) == STATE_DEGRADED
    assert c.determine_state({"node1", "node2"}) == STATE_DOWN


# ---------------------------------------------------------------------------
# anti-entropy
# ---------------------------------------------------------------------------


def P(pairs):
    if not pairs:
        return np.empty(0, np.uint64), np.empty(0, np.uint64)
    r, c = zip(*pairs)
    return np.array(r, np.uint64), np.array(c, np.uint64)


def test_block_checksums_detect_difference():
    a = block_checksums(P([(0, 1), (0, 5), (150, 7)]))
    b = block_checksums(P([(0, 1), (0, 5), (150, 8)]))
    assert set(a) == {0, 1}
    assert a[0] == b[0]
    assert a[1] != b[1]
    assert diff_blocks(a, b) == [1]


def test_block_checksums_empty():
    assert block_checksums(P([])) == {}


def test_merge_block_two_replicas_union():
    # even split -> set wins (fragment.go:1917)
    a = P([(0, 1), (0, 2)])
    b = P([(0, 2), (0, 3)])
    sets, clears = merge_block(0, [a, b])
    # replica a must add (0,3); replica b must add (0,1); no clears
    assert [(int(r), int(c)) for r, c in zip(*sets[0])] == [(0, 3)]
    assert [(int(r), int(c)) for r, c in zip(*sets[1])] == [(0, 1)]
    assert all(len(r) == 0 for r, _ in clears)


def test_merge_block_three_replicas_majority():
    a = P([(0, 1), (0, 9)])
    b = P([(0, 1)])
    c = P([(0, 2)])
    sets, clears = merge_block(0, [a, b, c])
    # (0,1): 2/3 votes -> kept; c must set it
    assert (0, 1) in [(int(r), int(cc)) for r, cc in zip(*sets[2])]
    # (0,9) and (0,2): 1/3 votes -> cleared from their holders
    assert (0, 9) in [(int(r), int(cc)) for r, cc in zip(*clears[0])]
    assert (0, 2) in [(int(r), int(cc)) for r, cc in zip(*clears[2])]
    # b only needs nothing cleared
    assert len(clears[1][0]) == 0


def test_merge_block_ignores_out_of_block_pairs():
    a = P([(0, 1), (250, 2)])  # row 250 is in block 2
    b = P([])
    sets, clears = merge_block(0, [a, b])
    got = [(int(r), int(c)) for r, c in zip(*sets[1])]
    assert got == [(0, 1)]


def test_merge_convergence_end_to_end():
    rng = np.random.default_rng(3)
    replicas = []
    for _ in range(3):
        n = rng.integers(50, 150)
        rows = rng.integers(0, 100, n).astype(np.uint64)
        cols = rng.integers(0, 1000, n).astype(np.uint64)
        replicas.append((rows, cols))
    sets, clears = merge_block(0, replicas)

    def apply(rep, s, cl):
        have = {(int(r), int(c)) for r, c in zip(*rep)}
        have |= {(int(r), int(c)) for r, c in zip(*s)}
        have -= {(int(r), int(c)) for r, c in zip(*cl)}
        return have

    states = [apply(rep, s, cl) for rep, s, cl in zip(replicas, sets, clears)]
    assert states[0] == states[1] == states[2]


# ---------------------------------------------------------------------------
# fragment integration
# ---------------------------------------------------------------------------


def test_fragment_block_sync_roundtrip():
    from pilosa_tpu.core.fragment import Fragment

    fa = Fragment(None, "i", "f", "standard", 0).open()
    fb = Fragment(None, "i", "f", "standard", 0).open()
    fa.bulk_import(np.array([0, 0, 1, 205]), np.array([3, 4, 9, 11]))
    fb.bulk_import(np.array([0, 1, 205]), np.array([3, 9, 12]))

    diffs = diff_blocks(fa.block_checksums(), fb.block_checksums())
    assert diffs == [0, 2]
    for bid in diffs:
        sets, clears = merge_block(bid, [fa.block_pairs(bid), fb.block_pairs(bid)])
        fa.apply_deltas(sets[0], clears[0])
        fb.apply_deltas(sets[1], clears[1])
    assert diff_blocks(fa.block_checksums(), fb.block_checksums()) == []
    assert fa.pairs()[1].tolist() == fb.pairs()[1].tolist()


def test_fragment_stream_roundtrip(tmp_path):
    from pilosa_tpu.core.fragment import Fragment

    src = Fragment(None, "i", "f", "standard", 3).open()
    src.bulk_import(np.array([0, 5, 7]), np.array([10, 20, 30]))
    blob = src.to_bytes()

    dst = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 3).open()
    dst.from_bytes(blob)
    assert dst.pairs()[0].tolist() == src.pairs()[0].tolist()
    assert dst.pairs()[1].tolist() == src.pairs()[1].tolist()
    # persisted: reopen from disk
    dst.close()
    dst2 = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 3).open()
    assert dst2.pairs()[1].tolist() == src.pairs()[1].tolist()


def test_fragment_stream_rejects_wrong_shard():
    from pilosa_tpu.core.fragment import Fragment

    src = Fragment(None, "i", "f", "standard", 3).open()
    src.bulk_import(np.array([0]), np.array([10]))
    dst = Fragment(None, "i", "f", "standard", 5).open()
    with pytest.raises(ValueError):
        dst.from_bytes(src.to_bytes())


def test_mod_hasher():
    c = make_cluster(3, hasher=ModHasher())
    assert [c.hasher.hash(k, 3) for k in range(6)] == [0, 1, 2, 0, 1, 2]


# ---------------------------------------------------------------------------
# Live elastic resize: streaming resharding under traffic (ISSUE 7).
# Fragment-level write capture, the coordinator's streaming job FSM, the
# deterministic kill-source / kill-destination / kill-coordinator matrix,
# abort/rollback invariants, and the no-global-freeze acceptance checks.
# ---------------------------------------------------------------------------

import json as _json
import threading
import time
import urllib.error
import urllib.request

from pilosa_tpu.core import wal as walmod
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.devcache import DEVICE_CACHE
from pilosa_tpu.core.fragment import (
    Fragment,
    TransferCaptureLost,
    TransferCutover,
)
from pilosa_tpu.server import faults
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def http_json(method, url, body=None, timeout=30):
    data = _json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return _json.loads(raw) if raw else {}


def http_err(method, url, body=None):
    """(status, parsed error body) of a request expected to fail."""
    try:
        http_json(method, url, body)
    except urllib.error.HTTPError as e:
        raw = e.read().decode("utf-8", "replace")
        try:
            return e.code, _json.loads(raw)
        except ValueError:
            return e.code, {"error": raw}
    raise AssertionError(f"{method} {url} unexpectedly succeeded")


def wait_job(uri, want="DONE", timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = http_json("GET", f"{uri}/cluster/resize/job")
        if job["state"] != "RUNNING":
            assert job["state"] == want, job
            return job
        time.sleep(0.05)
    raise AssertionError("resize job did not finish")


def row_columns(server, index, field):
    (res,) = server.api.query(index, f"Row({field}=0)")
    return sorted(int(x) for x in res.columns().tolist())


def transfer_state_clean(*servers):
    """Every node's transfer plane must be empty (captures + ledgers)."""
    for s in servers:
        assert s._transfer_captures == {}, s.node.id
        assert s._resize_ledger == {}, s.node.id


# -- fragment write capture -------------------------------------------------


def test_capture_roundtrip_streams_and_replays():
    """Snapshot + captured delta == the source's final state: every write
    shape (batched set, staged set, clear, word-level row union) taken
    after begin_streaming replays bit-identically on the destination."""
    src = Fragment(None, "i", "f", "standard", 0).open()
    src.bulk_import(np.array([0, 1]), np.array([3, 9]))
    blob = src.begin_streaming()
    # writes landing DURING the transfer, one of each funnel
    src.bulk_import(np.array([0]), np.array([7]))
    src.stage_positions(np.array([2 * SHARD_WIDTH + 5], np.uint64))
    src.clear_bit(1, 9)
    words = np.zeros(SHARD_WIDTH // 32, np.uint32)
    words[0] = 0b1000
    src.import_row_words(5, words)

    dst = Fragment(None, "i", "f", "standard", 0).open()
    dst.from_bytes(blob)
    assert dst.pairs()[1].tolist() != src.pairs()[1].tolist()  # snapshot lags
    applied = dst.apply_transfer_records(src.drain_capture())
    assert applied > 0
    assert dst.pairs()[0].tolist() == src.pairs()[0].tolist()
    assert dst.pairs()[1].tolist() == src.pairs()[1].tolist()
    # the drain is a read barrier: a second drain is empty, not a replay
    assert dst.apply_transfer_records(src.drain_capture()) == 0
    src.end_capture()
    with pytest.raises(TransferCaptureLost):
        src.drain_capture()


def test_capture_overflow_forces_refetch(monkeypatch):
    """A capture outgrowing its bound is dropped and the next drain says
    LOST (-> HTTP 410 -> the destination refetches) instead of this node
    buffering an unbounded delta for a dead driver."""
    monkeypatch.setattr(fragment_mod, "CAPTURE_MAX_POSITIONS", 4)
    f = Fragment(None, "i", "f", "standard", 0).open()
    f.begin_streaming()
    f.bulk_import(np.zeros(10, np.uint64), np.arange(10, dtype=np.uint64))
    with pytest.raises(TransferCaptureLost):
        f.drain_capture()
    # re-arming works and starts clean
    f.begin_streaming()
    assert f.drain_capture() == b""
    f.end_capture()


def test_capture_per_destination_independence():
    """Two destinations stream the same source fragment (replica_n > 1
    places a moving shard on several new owners): each gets its OWN
    capture — one leg's drain must not steal records the other never
    sees, and one leg's re-begin must not reset the other's buffer."""
    src = Fragment(None, "i", "f", "standard", 0).open()
    src.bulk_import(np.array([0]), np.array([1]))
    blob_a = src.begin_streaming("j:a")
    src.bulk_import(np.array([0]), np.array([2]))
    blob_b = src.begin_streaming("j:b")  # must not reset j:a
    src.bulk_import(np.array([0]), np.array([3]))
    da = Fragment(None, "i", "f", "standard", 0).open()
    da.from_bytes(blob_a)
    db = Fragment(None, "i", "f", "standard", 0).open()
    db.from_bytes(blob_b)
    da.apply_transfer_records(src.drain_capture("j:a"))
    db.apply_transfer_records(src.drain_capture("j:b"))
    assert da.pairs()[1].tolist() == src.pairs()[1].tolist()
    assert db.pairs()[1].tolist() == src.pairs()[1].tolist()
    src.end_capture("j:a")
    with pytest.raises(TransferCaptureLost):
        src.drain_capture("j:a")
    assert src.drain_capture("j:b") == b""  # j:b survives a's teardown
    src.end_capture()


def test_wholesale_replace_invalidates_capture():
    """from_bytes replaces contents outside the snapshot+delta contract:
    an armed capture must flip to LOST, never stream a bogus delta."""
    other = Fragment(None, "i", "f", "standard", 0).open()
    other.bulk_import(np.array([9]), np.array([1]))
    f = Fragment(None, "i", "f", "standard", 0).open()
    f.begin_streaming()
    f.from_bytes(other.to_bytes())
    with pytest.raises(TransferCaptureLost):
        f.drain_capture()


def test_mutex_import_retry_after_cutover_barrier():
    """A mutex bulk import rejected by the cutover write barrier must be
    cleanly retryable: the mutex map may only advance when the bits land
    (regression: the map was updated before import_positions raised
    TransferCutover, so the retry saw existing == row and silently
    dropped the write — map and bitmap permanently divergent)."""
    f = Fragment(None, "i", "m", "standard", 0, mutex=True).open()
    f.bulk_import(np.array([1]), np.array([7]))
    f.block_writes(30.0)
    with pytest.raises(TransferCutover):
        f.bulk_import(np.array([2]), np.array([7]))
    f.unblock_writes()
    # the retry is NOT a no-op: row 2 wins the column, row 1 cleared
    assert f.bulk_import(np.array([2]), np.array([7])) == 1
    rows, cols = f.pairs()
    assert list(zip(rows.tolist(), cols.tolist())) == [(2, 7)]
    assert f._mutex_map == {7: 2}


def test_decode_records_strict_on_torn_stream():
    """The wire codec must fail loudly on truncation/corruption — a torn
    delta silently applied as a prefix would be data loss."""
    data = walmod.encode_records(
        [(walmod.OP_SET, np.array([1, 2, 3], np.uint64))]
    )
    got = list(walmod.decode_records(data))
    assert len(got) == 1 and got[0][1].tolist() == [1, 2, 3]
    with pytest.raises(ValueError):
        list(walmod.decode_records(data[:-3]))
    bad = bytearray(data)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError):
        list(walmod.decode_records(bytes(bad)))


# -- streaming join: no freeze, no lost writes ------------------------------


def test_streaming_join_no_freeze_and_no_lost_writes():
    """The tier-1 deterministic acceptance core: mid-job (cutover phase,
    pre-commit) the cluster still ACCEPTS WRITES and admits queries in
    state NORMAL — no global freeze — and those racing writes are
    bit-identically present on every node after the job commits (the
    post-cutover drain ships them to the moved fragments' new owners)."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("lj")
        api.create_field("lj", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + s for s in range(16)]
        api.import_bits("lj", "f", [0] * len(cols), cols)
        extra = [s * SHARD_WIDTH + 100 for s in range(16)]
        joiner = NodeServer(None, "stream-joiner").start()
        during = {}

        def hook(phase):
            if phase == "cutover":
                during["state"] = c[0].state
                during["job"] = c[0].resize_job["state"]
                api.import_bits("lj", "f", [0] * len(extra), extra)
                (during["count"],) = api.query("lj", "Count(Row(f=0))")

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri)
            assert during["state"] == "NORMAL"  # never froze
            assert during["job"] == "RUNNING"
            assert during["count"] == len(cols) + len(extra)
            assert job["committed"] is True
            assert job["transfers"], job
            model = sorted(set(cols + extra))
            for s in [c[0], c[1], joiner]:
                assert row_columns(s, "lj", "f") == model, s.node.id
            # the joiner actually serves moved fragments
            assert any(
                n.id == joiner.node.id
                for sh in range(16)
                for n in c[0].cluster.shard_nodes("lj", sh)
            )
            transfer_state_clean(c[0], c[1], joiner)
        finally:
            c[0].resize_phase_hook = None
            joiner.stop()


# -- deterministic kill matrix ----------------------------------------------


def test_resize_kill_source_aborts_cleanly():
    """kill-source: every snapshot fetch refused (the source is dead to
    the transfer plane) -> the job aborts and rolls back with NO trace:
    old topology, zero repair debt, no leftover captures/ledgers, device
    residency unchanged; the cluster keeps serving and a later join
    succeeds."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("ks")
        api.create_field("ks", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 3 for s in range(16)]
        api.import_bits("ks", "f", [0] * len(cols), cols)
        (pre_cnt,) = api.query("ks", "Count(Row(f=0))")
        pre_bytes = DEVICE_CACHE.stats_snapshot()["resident_bytes"]
        old_ids = {n.id for n in c[0].cluster.nodes}
        joiner = NodeServer(None, "ks-joiner").start()
        inj = faults.FaultInjector(seed=7)
        inj.add_rule("refuse", path="/internal/fragment/data")
        faults.install_injector(inj)
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=120)
            assert job["error"]
            assert inj.count("refuse") > 0  # the fault actually fired
            for s in [c[0], c[1]]:
                assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
                assert s.state == "NORMAL"
                assert s.holder.pending_repair_count() == 0
            assert [n.id for n in joiner.cluster.nodes] == [joiner.node.id]
            assert joiner.holder.index("ks") is None or not any(
                v.fragments
                for f in joiner.holder.index("ks").fields(include_hidden=True)
                for v in f.views.values()
            )
            transfer_state_clean(c[0], c[1], joiner)
            assert (
                DEVICE_CACHE.stats_snapshot()["resident_bytes"] == pre_bytes
            )
            faults.uninstall_injector()
            (cnt,) = api.query("ks", "Count(Row(f=0))")
            assert cnt == pre_cnt
            # the transfer plane healed: the same join now succeeds
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(c[0].node.uri, timeout=120)
            for s in [c[0], c[1], joiner]:
                (cnt,) = s.api.query("ks", "Count(Row(f=0))")
                assert cnt == pre_cnt, s.node.id
        finally:
            faults.uninstall_injector()
            joiner.stop()


def test_resize_kill_destination_aborts_cleanly():
    """kill-destination (remove-node shape, so members DO move data and
    arm captures): the second destination's stream step is unreachable ->
    abort. The first destination's fetched fragments are deleted and the
    sources' captures released by the rollback broadcast — pre-resize
    state everywhere, data still fully served."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("kd")
        api.create_field("kd", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 5 for s in range(24)]
        api.import_bits("kd", "f", [0] * len(cols), cols)
        old_ids = {n.id for n in c[0].cluster.nodes}
        captured_mid = {}

        def hook(phase):
            if phase == f"stream:{c[1].node.id}":
                # first destination (the coordinator) streamed already:
                # captures must be armed on its sources right now
                captured_mid["n"] = sum(
                    len(s._transfer_captures) for s in c.nodes
                )

        c[0].resize_phase_hook = hook
        inj = faults.FaultInjector(seed=11)
        inj.add_rule(
            "refuse", uri=c[1].node.uri, path="/internal/resize/stream"
        )
        faults.install_injector(inj)
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/resize/remove-node",
                {"id": c[2].node.id},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=120)
            assert job["error"]
            # the coordinator really did move fragments before the abort
            assert captured_mid.get("n", 0) > 0
            for s in c.nodes:
                assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
                assert s.state == "NORMAL"
                assert s.holder.pending_repair_count() == 0
            transfer_state_clean(*c.nodes)
            # holder contents match pre-resize placement: nobody kept a
            # fragment the OLD topology does not assign to them
            for s in c.nodes:
                idx = s.holder.index("kd")
                for f in idx.fields(include_hidden=True):
                    for v in f.views.values():
                        for shard in v.fragments:
                            owners = {
                                n.id
                                for n in s.cluster.shard_nodes("kd", shard)
                            }
                            assert s.node.id in owners, (s.node.id, shard)
            faults.uninstall_injector()
            for s in c.nodes:
                (cnt,) = s.api.query("kd", "Count(Row(f=0))")
                assert cnt == len(cols), s.node.id
        finally:
            c[0].resize_phase_hook = None
            faults.uninstall_injector()


def test_resize_kill_coordinator_mid_job_cluster_survives():
    """kill-coordinator: the coordinator loses its network mid-stream
    (per-client partition — the in-process stand-in for a coordinator
    crash). The job aborts; members never switched topology, so the
    cluster keeps serving the old placement; after the partition heals a
    fresh join runs to DONE (stale transfer state is superseded, not
    wedged)."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("kc")
        api.create_field("kc", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 8 for s in range(16)]
        api.import_bits("kc", "f", [0] * len(cols), cols)
        old_ids = {n.id for n in c[0].cluster.nodes}
        joiner = NodeServer(None, "kc-joiner").start()
        inj = faults.FaultInjector(seed=13)
        c[0].client.fault_injector = inj

        def hook(phase):
            if phase == f"stream:{joiner.node.id}":
                inj.add_rule("partition")  # cut the coordinator off fully

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=120)
            assert job["error"]
            # the member never heard about any of it: old topology, serving
            assert {n.id for n in c[1].cluster.nodes} == old_ids
            assert c[1].state == "NORMAL"
            (cnt,) = c[1].api.query("kc", "Count(Row(f=0))")
            assert cnt == len(cols)
            # the joiner was never admitted
            assert [n.id for n in joiner.cluster.nodes] == [joiner.node.id]
            # heal: the coordinator re-learns its peers and retries clean
            inj.heal()
            c[0].resize_phase_hook = None
            c[0].probe_peers()
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(c[0].node.uri, timeout=120)
            for s in [c[0], c[1], joiner]:
                (cnt,) = s.api.query("kc", "Count(Row(f=0))")
                assert cnt == len(cols), s.node.id
            transfer_state_clean(c[0], c[1], joiner)
        finally:
            c[0].resize_phase_hook = None
            c[0].client.fault_injector = None
            joiner.stop()


# -- abort / rollback invariants --------------------------------------------


def test_abort_mid_stream_restores_pre_resize_state():
    """Operator abort after the first destination streamed: topology,
    pending-repair debt, and device-cache residency all read EXACTLY as
    pre-resize, and the same resize then runs to DONE."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("ab")
        api.create_field("ab", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 2 for s in range(24)]
        api.import_bits("ab", "f", [0] * len(cols), cols)
        model = row_columns(c[0], "ab", "f")
        pre_bytes = DEVICE_CACHE.stats_snapshot()["resident_bytes"]
        old_ids = {n.id for n in c[0].cluster.nodes}
        pre_frags = {
            s.node.id: sorted(
                (f.name, vn, sh)
                for f in s.holder.index("ab").fields(include_hidden=True)
                for vn, v in f.views.items()
                for sh in v.fragments
            )
            for s in c.nodes
        }

        def hook(phase):
            if phase == f"stream:{c[1].node.id}":
                c[0].abort_resize()

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/resize/remove-node",
                {"id": c[2].node.id},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=120)
            assert job["error"] == "aborted"
            for s in c.nodes:
                assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
                assert s.state == "NORMAL"
                assert s.holder.pending_repair_count() == 0
                got = sorted(
                    (f.name, vn, sh)
                    for f in s.holder.index("ab").fields(include_hidden=True)
                    for vn, v in f.views.items()
                    for sh in v.fragments
                )
                assert got == pre_frags[s.node.id], s.node.id
            transfer_state_clean(*c.nodes)
            # no LEAKED residency: the deleted transfer fragments freed
            # their device bytes (warm view stacks may legitimately have
            # dropped — fragment creation fires on_mutate — so this is a
            # <=, and the re-query below proves the cache rebuilds)
            assert (
                DEVICE_CACHE.stats_snapshot()["resident_bytes"] <= pre_bytes
            )
            assert row_columns(c[0], "ab", "f") == model
            # the aborted resize re-runs clean
            c[0].resize_phase_hook = None
            http_json(
                "POST", f"{c[0].node.uri}/cluster/resize/remove-node",
                {"id": c[2].node.id},
            )
            wait_job(c[0].node.uri, timeout=120)
            for s in [c[0], c[1]]:
                assert row_columns(s, "ab", "f") == model, s.node.id
        finally:
            c[0].resize_phase_hook = None


def test_abort_after_joiner_streamed_deletes_joiner_fragments():
    """Abort AFTER the joiner's stream step completed: the rollback
    resets the joiner to a solo cluster — which owns every shard, so the
    cleanup's stale-ledger ownership guard must not apply on the abort
    path (regression: the joiner kept, and served, every fetched
    fragment after 'rolling back'). The joiner carries the schema
    already (a rejoining ex-member): a schema-less joiner fetches
    nothing pre-commit (its legs are all skipped as field-gone and its
    data ships in the post-commit sweep), so only this shape reaches
    the guard with created fragments."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("aj")
        api.create_field("aj", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 11 for s in range(16)]
        api.import_bits("aj", "f", [0] * len(cols), cols)
        old_ids = {n.id for n in c[0].cluster.nodes}
        joiner = NodeServer(None, "zz-joiner").start()
        joiner.api.create_index("aj")
        joiner.api.create_field("aj", "f", {"type": "set"})
        streamed = {}

        def hook(phase):
            if phase == "cutover":
                idx = joiner.holder.index("aj")
                streamed["frags"] = sum(
                    len(v.fragments)
                    for f in idx.fields(include_hidden=True)
                    for v in f.views.values()
                )
                c[0].abort_resize()

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=120)
            assert job["error"] == "aborted"
            # the joiner streamed real fragments before the abort...
            assert streamed["frags"] > 0, "scenario failed to stream to joiner"
            # ...and the rollback deleted ALL of them: a solo node that
            # owns_shard()s everything still must not keep fetched data
            assert [n.id for n in joiner.cluster.nodes] == [joiner.node.id]
            idx = joiner.holder.index("aj")
            assert idx is None or not any(
                v.fragments
                for f in idx.fields(include_hidden=True)
                for v in f.views.values()
            ), "joiner kept fetched fragments after rollback"
            for s in [c[0], c[1]]:
                assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
                assert s.state == "NORMAL"
            transfer_state_clean(c[0], c[1], joiner)
            # the same join re-runs clean afterwards
            c[0].resize_phase_hook = None
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(c[0].node.uri, timeout=120)
            model = sorted(cols)
            for s in [c[0], c[1], joiner]:
                assert row_columns(s, "aj", "f") == model, s.node.id
        finally:
            c[0].resize_phase_hook = None
            joiner.stop()


def test_abort_after_commit_is_noop():
    """Once the cutover install is acknowledged the job is COMMITTED: an
    abort must not race a rollback broadcast against the already-applied
    NORMAL install — the job rolls forward to DONE on the new topology."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("cm")
        api.create_field("cm", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 4 for s in range(12)]
        api.import_bits("cm", "f", [0] * len(cols), cols)
        joiner = NodeServer(None, "cm-joiner").start()

        def hook(phase):
            if phase == "committed":
                res = c[0].abort_resize()
                assert res["state"] == "RUNNING"  # record, not rolled back

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri, timeout=120)  # DONE, not ABORTED
            assert job["committed"] is True
            for s in [c[0], c[1], joiner]:
                assert len(s.cluster.nodes) == 3, s.node.id
                assert s.state == "NORMAL"
                (cnt,) = s.api.query("cm", "Count(Row(f=0))")
                assert cnt == len(cols), s.node.id
        finally:
            c[0].resize_phase_hook = None
            joiner.stop()


# -- handler coercion for the resize surface --------------------------------


def test_resize_surface_coercion_400s():
    """Malformed bodies on the resize control surface -> 400 JSON naming
    the field (the import/export coercion convention), never a 500."""
    with ClusterHarness(1, in_memory=True) as c:
        uri = c[0].node.uri
        code, body = http_err("POST", f"{uri}/internal/resize/stream", {})
        assert code == 400 and "job" in body["error"]
        code, body = http_err(
            "POST", f"{uri}/internal/resize/stream",
            {"job": "j", "nodes": "nope"},
        )
        assert code == 400 and "nodes" in body["error"]
        code, body = http_err(
            "POST", f"{uri}/internal/resize/stream",
            {"job": "j", "nodes": [{"uri": "u"}]},
        )
        assert code == 400 and "nodes" in body["error"] and "[0]" in body["error"]
        code, body = http_err(
            "POST", f"{uri}/internal/resize",
            {"nodes": [{"id": "a"}], "replicaN": "two"},
        )
        assert code == 400 and "replicaN" in body["error"]
        code, body = http_err("POST", f"{uri}/internal/resize", [1, 2])
        assert code == 400 and "JSON object" in body["error"]
        code, body = http_err(
            "POST", f"{uri}/internal/resize/catchup", {"job": ""}
        )
        assert code == 400 and "job" in body["error"]
        code, body = http_err("POST", f"{uri}/cluster/resize/remove-node", {})
        assert code == 400 and "id" in body["error"]
        code, body = http_err("POST", f"{uri}/cluster/join", {"id": "x"})
        assert code == 400 and "uri" in body["error"]
        c[0].api.create_index("cx")
        c[0].api.create_field("cx", "f", {"type": "set"})
        code, body = http_err(
            "GET", f"{uri}/internal/fragment/delta?index=cx&field=f&shard=0"
        )
        assert code == 400 and "job" in body["error"]
        # well-formed delta request with no armed capture -> 410 Gone
        code, body = http_err(
            "GET",
            f"{uri}/internal/fragment/delta?index=cx&field=f&shard=0&job=j1",
        )
        assert code == 410 and "capture" in body["error"]


# -- deterministic chaos subset (tier-1) ------------------------------------


def test_chaos_deterministic_add_under_faults():
    """Tier-1 chaos subset (no wall-clock races): a join runs while the
    fault injector serves counted 500s on the transfer plane (absorbed by
    the retry plane / resume policy) and writes land at exact FSM points
    via the phase hook. Zero wrong answers: every node ends bit-identical
    to the model, and the mid-job queries were admitted in state NORMAL."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("cd")
        api.create_field("cd", "f", {"type": "set"})
        model = set()

        def put(cols):
            api.import_bits("cd", "f", [0] * len(cols), cols)
            model.update(cols)

        put([s * SHARD_WIDTH + 1 for s in range(24)])
        joiner = NodeServer(None, "cd-joiner").start()
        inj = faults.FaultInjector(seed=5)
        # counted faults: two snapshot fetches and one stream instruction
        # fail with 500 before succeeding — the retry plane must absorb
        # them without the job noticing
        inj.add_rule("http500", path="/internal/fragment/data", times=2)
        inj.add_rule("http500", path="/internal/resize/stream", times=1)
        faults.install_injector(inj)
        admitted = []

        def hook(phase):
            if phase.startswith("stream:") or phase == "cutover":
                n = len(admitted)
                put([s * SHARD_WIDTH + 300 + n for s in range(8)])
                (cnt,) = api.query("cd", "Count(Row(f=0))")
                assert cnt == len(model)
                admitted.append(c[0].state)

        c[0].resize_phase_hook = hook
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(c[0].node.uri, timeout=120)
            assert inj.count("http500") == 3  # every scripted fault fired
            assert admitted and all(s == "NORMAL" for s in admitted)
            expect = sorted(model)
            for s in [c[0], c[1], c[2], joiner]:
                assert row_columns(s, "cd", "f") == expect, s.node.id
            # the joiner's own stats saw real transfer work
            snap = joiner.stats.registry.snapshot()
            assert snap.get("resize.fragments_streamed", 0) > 0
            transfer_state_clean(c[0], c[1], c[2], joiner)
        finally:
            c[0].resize_phase_hook = None
            faults.uninstall_injector()
            joiner.stop()


# -- chaos soak (slow): add a node AND kill a node mid-workload --------------


@pytest.mark.slow
def test_chaos_soak_add_then_kill_under_traffic():
    """The ISSUE 7 acceptance soak: concurrent ingest + queries with the
    fault injector flaking the internode plane, while a node JOINS and
    then a node is KILLED and removed. Zero wrong answers (every node
    bit-identical to the single-process model at the end), queries
    admitted during the entire resize (no global freeze), and bounded
    p99 inflation read back from the flight-recorder histograms."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("cs")
        api.create_field("cs", "f", {"type": "set"})
        lock = threading.Lock()
        # zero-wrong-answers contract under availability-first writes:
        # `model` holds writes the import summary confirmed FULLY
        # replicated (those must survive any single-node kill);
        # `intended` holds everything issued (a write acked by only one
        # replica may die with that replica — reported, not silent).
        # Final results must satisfy model <= result <= intended.
        model = set()
        intended = set()

        def put(cols):
            with lock:
                intended.update(cols)
            # a write hitting the per-fragment cutover barrier surfaces
            # as retryable (HTTP 503 + Retry-After for wire clients);
            # model that client behavior — the barrier window is bounded,
            # so the retry always lands (idempotent set bits)
            for _ in range(100):
                try:
                    s = api.import_bits("cs", "f", [0] * len(cols), cols)
                    break
                except TransferCutover:
                    time.sleep(0.02)
            else:
                raise AssertionError("cutover barrier never lifted")
            if s["applied"] == s["expected"] and not s["errors"]:
                with lock:
                    model.update(cols)

        put([s * SHARD_WIDTH + 7 for s in range(16)])
        # baseline latency before any resize traffic
        for _ in range(30):
            api.query("cs", "Count(Row(f=0))")
        reg = c[0].stats.registry
        p99_base = reg.quantile("query_ms", 0.99, tags=("index:cs",))
        assert p99_base > 0

        stop = threading.Event()
        failures = []
        during_resize_queries = [0]

        def ingester():
            i = 0
            while not stop.is_set():
                base = 1000 + i * 40
                try:
                    put([
                        (k % 16) * SHARD_WIDTH + base + k for k in range(40)
                    ])
                except Exception as e:  # noqa: BLE001 - collected for assert
                    failures.append(("ingest", repr(e)))
                i += 1
                time.sleep(0.02)

        def querier():
            while not stop.is_set():
                try:
                    job = c[0].resize_job
                    running = job is not None and job["state"] == "RUNNING"
                    (cnt,) = api.query("cs", "Count(Row(f=0))")
                    with lock:
                        upper = len(intended)
                    # no phantom bits, ever: a count may transiently lag
                    # during a cutover window, but it may never exceed
                    # what the workload has ISSUED (bits from nowhere)
                    if cnt > upper:
                        failures.append(("phantom", cnt, upper))
                    if running:
                        during_resize_queries[0] += 1
                except Exception as e:  # noqa: BLE001 - collected for assert
                    failures.append(("query", repr(e)))
                time.sleep(0.01)

        inj = faults.FaultInjector(seed=3)
        # seeded background flakiness across the whole internode plane;
        # absorbed by retry/breaker/resume
        inj.add_rule("http500", path="/internal/fragment", prob=0.05)
        faults.install_injector(inj)
        threads = [
            threading.Thread(target=ingester, daemon=True),
            threading.Thread(target=querier, daemon=True),
        ]
        joiner = NodeServer(None, "cs-joiner", replica_n=2).start()
        try:
            for t in threads:
                t.start()
            # -- elastic grow under traffic
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(c[0].node.uri, timeout=180)
            # -- kill a node mid-workload, then remove it under traffic
            c.stop_node(2)
            time.sleep(0.3)
            http_json(
                "POST", f"{c[0].node.uri}/cluster/resize/remove-node",
                {"id": c[2].node.id},
            )
            wait_job(c[0].node.uri, timeout=180)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not failures, failures[:5]
            assert during_resize_queries[0] > 0  # admitted THROUGH the jobs
            faults.uninstall_injector()
            # convergence: drain repair debt, then every live node must be
            # bit-identical to the model
            live = [c[0], c[1], joiner]
            for s in live:
                s.sync_holder()
            got = {s.node.id: row_columns(s, "cs", "f") for s in live}
            first = next(iter(got.values()))
            for nid, g in got.items():
                assert g == first, f"nodes diverged: {nid}"
                assert set(model) <= set(g) <= set(intended), nid
            # bounded p99 inflation (flight-recorder histogram, ms): the
            # resize ran on the batch class, so interactive latency may
            # grow but must stay in the same order of magnitude
            p99_all = reg.quantile("query_ms", 0.99, tags=("index:cs",))
            assert p99_all <= max(25.0 * p99_base, 2000.0), (
                p99_all, p99_base,
            )
        finally:
            stop.set()
            faults.uninstall_injector()
            joiner.stop()
