"""Durable write path: deterministic crash-kill matrix + group-commit
WAL properties.

Kill matrix (ISSUE 12): a real writer process (tests/crash_worker.py)
drives the staged import path with a FaultInjector "kill" rule armed at
one exact durability point — inside the group-commit round (pre-fsync,
post-fsync-pre-ack), during a replica ship, at the merge-barrier
install, between snapshot and WAL truncate — and SIGKILLs itself there.
The parent then audits the survivor state against the killed process's
fsynced ack log: every acked batch must replay bit-identically (rows
AND rank-cache order), and replay must be deterministic (two
independent opens agree). The full matrix (bounded-loss mode, replica
ship, soak) runs @slow in CI's mesh job.

Property layer: the torn-tail test truncates a group-committed WAL at
EVERY byte boundary and asserts replay recovers exactly the longest
valid CRC-framed prefix; the coalescing test drives >= 8 concurrent
importers and asserts fsyncs-per-import < 0.5 (the group commit
measurably coalesces); the solo-writer test pins the no-hold-window
contract (one fsync per import, latency within 2x of a bare
write+fsync)."""

import importlib.util
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pilosa_tpu.core import wal as walmod
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.server import faults
from pilosa_tpu.shardwidth import SHARD_WIDTH

_HERE = os.path.dirname(os.path.abspath(__file__))
_WORKER = os.path.join(_HERE, "crash_worker.py")

_spec = importlib.util.spec_from_file_location("crash_worker", _WORKER)
crash_worker = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(crash_worker)


@pytest.fixture(autouse=True)
def _strict_commit_mode():
    """Every test leaves the process-global committer in strict mode
    with no background syncer cadence armed."""
    yield
    walmod.GROUP_COMMIT.configure(sync_interval=0.0)


# ---------------------------------------------------------------------------
# kill-matrix driver
# ---------------------------------------------------------------------------


def _run_worker(tmp_path, point, sync_interval=0.0, kill_after=2,
                batches=30, n_shards=4, max_op_n=0, expect_kill=True,
                require_incomplete=True):
    data_dir = os.path.join(str(tmp_path), "data")
    ack_log = os.path.join(str(tmp_path), "acks.log")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    args = [
        sys.executable, _WORKER,
        "--point", point,
        "--data-dir", data_dir,
        "--ack-log", ack_log,
        "--sync-interval", str(sync_interval),
        "--batches", str(batches),
        "--kill-after", str(kill_after),
        "--n-shards", str(n_shards),
        "--max-op-n", str(max_op_n),
    ]
    proc = subprocess.run(
        args, env=env, capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(_HERE),
    )
    if expect_kill:
        # the injector must have SIGKILLed the worker mid-write — a
        # clean exit means the kill point never fired and the test
        # would be vacuous
        assert proc.returncode == -signal.SIGKILL, (
            point, proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:],
        )
        if require_incomplete:
            assert "COMPLETED" not in proc.stdout, proc.stdout
    acked = []
    if os.path.exists(ack_log):
        with open(ack_log) as fh:
            acked = [int(x) for x in fh.read().split()]
    return data_dir, acked


def _expected_positions(batch_ids, n_shards):
    want = set()
    for i in batch_ids:
        rows, cols = crash_worker.batch_bits(i, n_shards)
        shards = cols // SHARD_WIDTH
        in_shard = cols % SHARD_WIDTH
        want.update(
            zip(shards.tolist(), rows.tolist(), in_shard.tolist())
        )
    return want


def _state_of(data_dir, index="ck"):
    """(positions, cache_tops): the full replayed bit set as
    (shard, row, col) tuples plus each fragment's rank-cache top list."""
    h = Holder(data_dir).open()
    try:
        idx = h.index(index)
        assert idx is not None, f"index {index!r} missing after replay"
        f = idx.field("f")
        std = f.view("standard")
        got = set()
        tops = {}
        for shard, frag in sorted(std.fragments.items()):
            rows, cols = frag.pairs()
            got.update(
                (shard, int(r), int(c)) for r, c in zip(rows.tolist(), cols.tolist())
            )
            tops[shard] = list(frag.cache_top())
        return got, tops
    finally:
        h.close()


def _verify_replay(data_dir, acked, batches, n_shards, *, index="ck",
                   acked_must_survive=True):
    got1, tops1 = _state_of(data_dir, index)
    got2, tops2 = _state_of(data_dir, index)
    # replay is deterministic: two independent opens are bit-identical,
    # including the rank-cache (TopN) order
    assert got1 == got2
    assert tops1 == tops2
    sent = _expected_positions(range(batches), n_shards)
    assert got1 <= sent, "replay invented bits that were never written"
    if acked_must_survive:
        want = _expected_positions(acked, n_shards)
        missing = want - got1
        assert not missing, (
            f"{len(missing)} acked bits lost after crash replay "
            f"(acked batches {acked[:5]}..{acked[-1] if acked else None})"
        )
    return got1


# The tier-1 deterministic subset: one strict-mode kill at each
# single-process point. The full matrix (bounded-loss mode, replica
# ship) rides @slow below.
@pytest.mark.parametrize(
    "point,max_op_n",
    [
        ("commit.pre_fsync", 0),
        ("commit.post_fsync", 0),
        ("snapshot.pre_truncate", 400),
        ("merge.install", 0),
    ],
)
def test_kill_matrix_strict(tmp_path, point, max_op_n):
    data_dir, acked = _run_worker(
        tmp_path, point, sync_interval=0.0, kill_after=2, max_op_n=max_op_n
    )
    # the kill fired mid-batch: not every batch can have been acked
    assert len(acked) < 30, "worker finished all batches before the kill"
    _verify_replay(data_dir, acked, 30, 4)


@pytest.mark.slow
@pytest.mark.parametrize(
    "point,max_op_n",
    [
        ("commit.pre_fsync", 0),
        ("commit.post_fsync", 0),
        ("snapshot.pre_truncate", 400),
        ("merge.install", 0),
    ],
)
def test_kill_matrix_bounded_loss(tmp_path, point, max_op_n):
    """sync-interval > 0: acks outpace fsyncs by design. A process kill
    still loses nothing (the buffered bytes live in the OS page cache,
    which survives the process) — the loss window only opens on a
    machine crash, which is exactly what the torn-tail property test
    models at the byte level. Replay must stay deterministic and a
    subset of what was sent."""
    # require_incomplete=False: in bounded-loss mode the kill rides the
    # background syncer's cadence, so it may land only after the last
    # (already acked) batch — that is the mode's contract, not a miss
    data_dir, acked = _run_worker(
        tmp_path, point, sync_interval=0.05, kill_after=0,
        max_op_n=max_op_n, require_incomplete=False,
    )
    _verify_replay(data_dir, acked, 30, 4)


@pytest.mark.slow
def test_kill_during_replica_ship(tmp_path):
    """Kill the importing node while a pool thread is shipping a replica
    frame (2 real in-process nodes over HTTP). Both data dirs must
    replay deterministically; every ACKED batch survives on the
    coordinator (acks wait for local apply + ship resolution), and the
    replica holds a subset of what was sent."""
    data_dir, acked = _run_worker(
        tmp_path, "replica.ship", kill_after=3, batches=20,
    )
    got_a = _verify_replay(os.path.join(data_dir, "a"), acked, 20, 4)
    got_b = _verify_replay(
        os.path.join(data_dir, "b"), acked, 20, 4, acked_must_survive=False
    )
    # acked writes reached the coordinator; the replica may trail by
    # the in-flight frame only (anti-entropy repairs the rest, as the
    # pending-repair ledger records)
    assert got_b <= _expected_positions(range(20), 4)
    assert len(got_a) >= len(got_b)


# ---------------------------------------------------------------------------
# torn-tail property: replay recovers exactly the longest valid prefix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sync_interval", [0.0, 0.05])
def test_torn_tail_every_byte_boundary(tmp_path, sync_interval):
    walmod.GROUP_COMMIT.configure(sync_interval=sync_interval)
    rng = np.random.default_rng(7)
    records = [
        (walmod.OP_SET, rng.integers(0, 1 << 40, 37).astype(np.uint64)),
        (walmod.OP_CLEAR, rng.integers(0, 1 << 40, 11).astype(np.uint64)),
        # an OP_ROW_WORDS frame: payload[0] = row id, rest = row words
        (walmod.OP_ROW_WORDS, rng.integers(0, 1 << 60, 33).astype(np.uint64)),
        (walmod.OP_SET, rng.integers(0, 1 << 40, 23).astype(np.uint64)),
    ]
    p = str(tmp_path / "torn.wal")
    w = walmod.WalWriter(p)
    for op, positions in records:
        w.append(op, positions)
    walmod.GROUP_COMMIT.wait_durable()
    w.close()
    data = open(p, "rb").read()
    # record byte spans: header (13 bytes) + 8 bytes per position
    spans = []
    off = 0
    for op, positions in records:
        off += walmod._REC_HDR.size + 8 * len(positions)
        spans.append(off)
    assert spans[-1] == len(data)
    trunc = str(tmp_path / "trunc.wal")
    for cut in range(len(data) + 1):
        with open(trunc, "wb") as fh:
            fh.write(data[:cut])
        replayed = list(walmod.replay_wal(trunc))
        # the longest valid prefix: every record whose bytes fit in the cut
        n_want = sum(1 for s in spans if s <= cut)
        assert len(replayed) == n_want, (cut, n_want, len(replayed))
        for (op_w, pos_w), (op_g, pos_g) in zip(records, replayed):
            assert op_w == op_g
            np.testing.assert_array_equal(pos_w, pos_g)
        n_ops, status, _ = walmod.check_wal(trunc)
        assert n_ops == n_want
        assert status == ("ok" if cut in (0, *spans) else "torn")


def test_append_skips_empty_records(tmp_path):
    p = str(tmp_path / "empty.wal")
    w = walmod.WalWriter(p)
    assert w.append(walmod.OP_SET, np.empty(0, np.uint64)) is None
    assert w.append_many([(walmod.OP_SET, np.empty(0, np.uint64))]) is None
    assert os.path.getsize(p) == 0
    # a mixed batch frames only the non-empty record
    tok = w.append_many(
        [
            (walmod.OP_SET, np.empty(0, np.uint64)),
            (walmod.OP_CLEAR, np.array([5, 9], np.uint64)),
        ]
    )
    assert tok is not None
    walmod.GROUP_COMMIT.wait_durable(tok)
    w.close()
    replayed = list(walmod.replay_wal(p))
    assert len(replayed) == 1
    assert replayed[0][0] == walmod.OP_CLEAR


def test_truncate_is_fsynced_and_dir_synced(tmp_path):
    # behavioural floor: a truncated WAL stays empty across reopen and
    # a fresh writer's file is immediately visible/replayable (the
    # fsync/dir-fsync calls themselves can only be proven on a real
    # power cut; this pins the code path end to end)
    p = str(tmp_path / "t.wal")
    w = walmod.WalWriter(p)
    tok = w.append(walmod.OP_SET, np.array([1, 2, 3], np.uint64))
    walmod.GROUP_COMMIT.wait_durable(tok)
    w.truncate()
    assert os.path.getsize(p) == 0
    assert list(walmod.replay_wal(p)) == []
    w.close()


# ---------------------------------------------------------------------------
# group-commit coalescing + solo-writer contract
# ---------------------------------------------------------------------------


def test_group_commit_coalesces_concurrent_imports(tmp_path):
    """Acceptance: >= 8 concurrent import threads, fsyncs-per-import
    < 0.5. An injected 3 ms fsync makes the rounds overlap the way a
    real disk does (on tmpfs an fsync is near-free and nothing would
    queue), so followers pile up behind the leader and each round
    releases several imports with ONE fsync."""
    inj = faults.FaultInjector(seed=0).add_wal_rule(
        "slow", point="wal.fsync", delay=0.003
    )
    faults.install_injector(inj)
    h = Holder(str(tmp_path)).open()
    try:
        idx = h.create_index("gc")
        f = idx.create_field("f", FieldOptions())
        # warm: create the fragment outside the measured window
        f.import_bits(np.array([0], np.uint64), np.array([0], np.uint64))
        walmod.GROUP_COMMIT.flush()
        s0 = walmod.stats_snapshot()
        per_thread = 15
        n_threads = 8
        errs = []

        def writer(t):
            try:
                rng = np.random.default_rng(t)
                for _ in range(per_thread):
                    rows = rng.integers(0, 4, 200).astype(np.uint64)
                    cols = rng.integers(0, SHARD_WIDTH, 200).astype(np.uint64)
                    f.import_bits(rows, cols)
            except Exception as e:  # noqa: BLE001 - fail the test
                errs.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:1]
        s1 = walmod.stats_snapshot()
        imports = n_threads * per_thread
        fsyncs = s1["fsyncs"] - s0["fsyncs"]
        groups = s1["commit_groups"] - s0["commit_groups"]
        assert fsyncs / imports < 0.5, (fsyncs, imports)
        assert groups <= fsyncs  # every round fsynced at least one file
    finally:
        faults.uninstall_injector()
        h.close()


@pytest.mark.skipif(
    os.environ.get("PILOSA_TPU_RACE_CHECK") == "1",
    reason="latency-budget assertion: the race checker's attribute "
    "instrumentation adds per-access overhead that blows the 2x-bare-"
    "fsync bound by design; the functional fsync-count assertions are "
    "covered by the rest of the matrix under the checker",
)
def test_solo_writer_strict_no_hold_window(tmp_path):
    """A solo strict-mode writer pays exactly one fsync round per import
    (the leader fires immediately — group commit adds no hold window)
    and its latency stays within 2x of a bare write+fsync."""
    h = Holder(str(tmp_path)).open()
    try:
        idx = h.create_index("solo")
        f = idx.create_field("f", FieldOptions())
        f.import_bits(np.array([0], np.uint64), np.array([0], np.uint64))
        walmod.GROUP_COMMIT.flush()
        s0 = walmod.stats_snapshot()
        n = 30
        rng = np.random.default_rng(3)
        gc_times = []
        for _ in range(n):
            rows = rng.integers(0, 4, 64).astype(np.uint64)
            cols = rng.integers(0, SHARD_WIDTH, 64).astype(np.uint64)
            t0 = time.perf_counter()
            f.import_bits(rows, cols)
            gc_times.append(time.perf_counter() - t0)
        s1 = walmod.stats_snapshot()
        # one commit round, one fsync per import — never more
        assert s1["fsyncs"] - s0["fsyncs"] <= n
        assert s1["commit_groups"] - s0["commit_groups"] <= n
        # bare write+fsync baseline on the same filesystem
        raw_path = str(tmp_path / "baseline.bin")
        data = walmod.encode_records(
            [(walmod.OP_SET, rng.integers(0, 1 << 40, 64).astype(np.uint64))]
        )
        naive_times = []
        with open(raw_path, "ab") as raw:
            for _ in range(n):
                t0 = time.perf_counter()
                raw.write(data)
                raw.flush()
                os.fsync(raw.fileno())
                naive_times.append(time.perf_counter() - t0)
        med_gc = sorted(gc_times)[n // 2]
        med_naive = sorted(naive_times)[n // 2]
        # 2x the bare fsync plus 2 ms absolute slack: the import also
        # stages positions and runs numpy, which a bare write does not
        assert med_gc <= 2 * med_naive + 0.002, (med_gc, med_naive)
    finally:
        h.close()


# ---------------------------------------------------------------------------
# replicated-ingest soak (@slow; the benched configuration's test twin)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_replicated_ingest_soak(tmp_path):
    """replica_n=2, two real HTTP nodes, 4 concurrent writers + a query
    stream: all writes converge on BOTH replicas, queries stay correct
    under ingest, and the group commit coalesces across the whole
    process (fsyncs-per-import < 2 with multi-shard batches)."""
    from pilosa_tpu.testing import ClusterHarness

    n_shards = 4
    with ClusterHarness(2, replica_n=2, base_dir=str(tmp_path)) as c:
        api = c[0].api
        api.create_index("soak")
        api.create_field("soak", "f", {"type": "set"})
        s0 = walmod.stats_snapshot()
        stop = threading.Event()
        sent = [set() for _ in range(4)]
        errs = []
        n_imports = [0]

        def writer(t):
            try:
                rng = np.random.default_rng(100 + t)
                for _ in range(12):
                    rows = np.zeros(500, np.uint64)
                    cols = rng.integers(
                        0, n_shards * SHARD_WIDTH, 500
                    ).astype(np.uint64)
                    api.import_bits("soak", "f", rows, cols)
                    sent[t].update(cols.tolist())
                    n_imports[0] += 1
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def reader():
            try:
                while not stop.is_set():
                    (cnt,) = c[1].api.query("soak", "Count(Row(f=0))")
                    assert cnt >= 0
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
        rt = threading.Thread(target=reader)
        for t in threads:
            t.start()
        rt.start()
        for t in threads:
            t.join()
        stop.set()
        rt.join()
        assert not errs, errs[:1]
        expect = len(set().union(*sent))
        for node in c.nodes:
            (cnt,) = node.api.query("soak", "Count(Row(f=0))")
            assert cnt == expect, node.node.id
        s1 = walmod.stats_snapshot()
        fsyncs = s1["fsyncs"] - s0["fsyncs"]
        appends = s1["commits"] - s0["commits"]
        # every append (data fragments AND the index's column-existence
        # tracking, on both replicas) is covered by strictly fewer
        # fsyncs: concurrent writers share commit rounds, so same-file
        # appends from different calls resolve under one fsync
        assert fsyncs < appends, (fsyncs, appends, n_imports[0])
