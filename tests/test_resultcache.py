"""Versioned result cache (core/resultcache.py): store units,
revalidation and incremental count repair (counter-asserted: zero
compiled dispatches, zero device reads, flat upload bytes on cached
hits), invalidation funnels, per-index GC, the admission cost discount,
and the differential harness — cached == recomputed bit-for-bit across
randomized set/clear/mutex/bulk interleavings on the single-node, HTTP
fan-out and mesh-group paths, with the naive model as the Count oracle.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from pilosa_tpu.core.naive import NaiveBitmap
from pilosa_tpu.core.resultcache import RESULT_CACHE, ResultCache
from pilosa_tpu.exec import plan as planmod
from pilosa_tpu.hbm import residency as hbm_res
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def _snap():
    return RESULT_CACHE.stats_snapshot()


def _seed_counts():
    s = _snap()
    return (
        s["hits"], s["misses"], s["repairs"], s["stores"],
        planmod.STATS["evals"], planmod.STATS["host_reads"],
        hbm_res.stats_snapshot()["restage_bytes"],
    )


def _harness(n=1, **kw):
    kw.setdefault("in_memory", True)
    kw.setdefault("telemetry_sample_interval", 0.0)
    return ClusterHarness(n, **kw)


def _seed(api, index="i", rows=(1, 2, 3), n=200, shards=2, seed=7):
    rng = np.random.default_rng(seed)
    api.create_index(index)
    api.create_field(index, "f")
    for r in rows:
        cols = rng.integers(0, shards * SHARD_WIDTH, n).astype(np.uint64)
        api.import_bits(index, "f", np.full(len(cols), r, np.uint64), cols)


# ---------------------------------------------------------------------------
# store units
# ---------------------------------------------------------------------------


def _vec(token, shards=(0, 1), versions=(0, 0)):
    return (("v", "", "f", "standard", token, tuple(shards), tuple(versions)),)


class TestStore:
    def test_lru_byte_budget_evicts_oldest(self):
        rc = ResultCache(budget_bytes=600)
        for i in range(8):
            rc.put((i, f"q{i}", (0,), False), "count", "i", f"q{i}", i, _vec(i))
        snap = rc.stats_snapshot()
        assert snap["resident_bytes"] <= 600
        assert snap["evictions"] > 0
        # the newest entry survived, the oldest did not
        assert rc.get((7, "q7", (0,), False), _vec(7))[0]
        assert not rc.get((0, "q0", (0,), False), _vec(0), recount=False)[0]

    def test_version_mismatch_misses(self):
        rc = ResultCache()
        rc.put(("k", "q", (0,), False), "count", "i", "q", 5, _vec(1))
        assert rc.get(("k", "q", (0,), False), _vec(1)) == (True, 5)
        found, _ = rc.get(("k", "q", (0,), False), _vec(1, versions=(0, 3)))
        assert not found

    def test_zero_budget_disables(self):
        rc = ResultCache(budget_bytes=0)
        rc.put(("k", "q", (0,), False), "count", "i", "q", 5, _vec(1))
        assert rc.stats_snapshot()["entries"] == 0
        assert rc.get(("k", "q", (0,), False), _vec(1)) == (False, None)

    def test_per_index_attribution_and_drop(self):
        rc = ResultCache()
        rc.put(("a", "q", (0,), False), "count", "idx_a", "q", 1, _vec(1))
        rc.put(("b", "q", (0,), False), "count", "idx_b", "q", 2, _vec(2))
        by = rc.stats_snapshot()["by_index"]
        assert set(by) == {"idx_a", "idx_b"} and all(v > 0 for v in by.values())
        rc.drop_index("idx_a")
        by = rc.stats_snapshot()["by_index"]
        assert set(by) == {"idx_b"}
        assert not rc.get(("a", "q", (0,), False), _vec(1), recount=False)[0]

    def test_note_mutation_drops_nonrepairable_only(self):
        rc = ResultCache()
        rc.put(("k", "t", (0,), False), "topn", "i", "t", [1], _vec(9))
        rc.put(
            ("k", "c", (0,), False), "count", "i", "c", 4, _vec(9),
            repair_row=1,
        )
        rc.note_mutation(9, 0)
        assert rc.stats_snapshot()["entries"] == 1  # the Count stayed
        rc.note_mutation(9, 5)  # uncovered shard: no-op
        assert rc.stats_snapshot()["entries"] == 1

    def test_mutated_results_do_not_poison_the_store(self):
        rc = ResultCache()
        pairs = [{"id": 1}]
        rc.put(("k", "t", (0,), False), "topn", "i", "t", pairs, _vec(3))
        pairs[0]["id"] = 99  # caller mutates its own copy post-store
        found, got = rc.get(("k", "t", (0,), False), _vec(3))
        assert found and got == [{"id": 1}]
        got[0]["id"] = 77  # reader mutates the served copy
        assert rc.get(("k", "t", (0,), False), _vec(3))[1] == [{"id": 1}]

    def test_has_text(self):
        rc = ResultCache()
        rc.put(("s", "q1", (0,), False), "count", "i", "q1", 1, _vec(1))
        assert rc.has_text("s", "q1")
        assert not rc.has_text("s", "q2")
        assert not rc.has_text(None, "q1")
        rc.drop_index("i")
        assert not rc.has_text("s", "q1")


# ---------------------------------------------------------------------------
# single-node revalidation: zero dispatches, zero device reads
# ---------------------------------------------------------------------------


class TestRevalidation:
    def test_count_topn_groupby_serve_with_zero_dispatches(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            api.create_field("i", "g")
            api.import_bits(
                "i", "g", np.full(64, 1, np.uint64),
                np.arange(64, dtype=np.uint64),
            )
            queries = [
                "Count(Intersect(Row(f=1), Row(f=2)))",
                "Count(Not(Row(f=1)))",
                "TopN(f, n=2)",
                "GroupBy(Rows(f), Rows(g))",
            ]
            cold = [api.query("i", q) for q in queries]
            h0, m0, _, _, e0, r0, u0 = _seed_counts()
            warm = [api.query("i", q) for q in queries]
            h1, m1, _, _, e1, r1, u1 = _seed_counts()
            assert warm == cold
            assert (e1 - e0, r1 - r0) == (0, 0)  # no dispatch, no read
            assert u1 - u0 == 0  # no host->device upload
            assert h1 - h0 == len(queries)
            assert m1 - m0 == 0

    def test_partial_hit_run_keeps_misses_batched(self):
        """One cached sibling in an adjacent-Count run must not degrade
        the misses to per-call dispatches: the miss subset still rides
        ONE multi-root batch."""
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            api.create_field("i", "g")
            api.import_bits(
                "i", "g", np.full(60, 1, np.uint64),
                np.arange(60, dtype=np.uint64),
            )
            api.import_bits(
                "i", "g", np.full(40, 2, np.uint64),
                np.arange(40, dtype=np.uint64),
            )
            q3 = "Count(Row(f=1))Count(Row(g=1))Count(Row(g=2))"
            want = api.query("i", q3)
            assert api.query("i", q3) == want  # all three cached
            api.query("i", "Set(99, g=1)")  # stale g entries, f still hot
            e0 = planmod.STATS["evals"]
            got = api.query("i", q3)
            assert got == [want[0], want[1] + 1, want[2]]
            # f served from cache; BOTH g misses shared one dispatch
            assert planmod.STATS["evals"] - e0 == 1, planmod.STATS

    def test_any_write_invalidates(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            q = "Count(Row(f=1))"
            before = api.query("i", q)[0]
            assert api.query("i", q)[0] == before
            api.query("i", f"Set({5 * SHARD_WIDTH - 1}, f=1)")
            after = api.query("i", q)[0]
            assert after == before + 1
            assert api.query("i", q)[0] == after

    def test_clear_invalidates(self):
        with _harness(1) as c:
            api = c[0].api
            api.create_index("i")
            api.create_field("i", "f")
            cols = np.arange(100, dtype=np.uint64)
            api.import_bits("i", "f", np.full(100, 1, np.uint64), cols)
            q = "Count(Row(f=1))"
            assert api.query("i", q)[0] == 100
            assert api.query("i", q)[0] == 100
            api.import_bits(
                "i", "f", np.full(40, 1, np.uint64), cols[:40], clear=True
            )
            assert api.query("i", q)[0] == 60
            assert api.query("i", q)[0] == 60

    def test_read_after_write_within_one_query(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            base = api.query("i", "Count(Row(f=1))")[0]
            api.query("i", "Count(Row(f=1))")  # cached
            col = 3 * SHARD_WIDTH // 2
            got = api.query(
                "i", f"Set({col}, f=1) Count(Row(f=1))"
            )
            assert got[1] == base + 1

    def test_time_args_are_ineligible(self):
        with _harness(1) as c:
            api = c[0].api
            api.create_index("i")
            api.create_field(
                "i", "t", {"type": "time", "time_quantum": "YMD"}
            )
            api.import_bits(
                "i", "t", np.full(10, 1, np.uint64),
                np.arange(10, dtype=np.uint64),
                timestamps=["2024-01-02T03:04"] * 10,
            )
            s0 = _snap()["stores"]
            q = "Count(Row(t=1, from='2024-01-01T00:00', to='2025-01-01T00:00'))"
            r1 = api.query("i", q)
            r2 = api.query("i", q)
            assert r1 == r2 == [10]
            assert _snap()["stores"] == s0  # never cached

    def test_profile_marks_cache_served_queries(self):
        """A sub-millisecond p50 must be attributable: profiled repeats
        carry a cache.hit span tag in the assembled trace (on the
        api.query root, or on the exec.batch span when the count
        batcher led the execution)."""

        def _tagged(node):
            if node["tags"].get("cache.hit"):
                return True
            return any(_tagged(ch) for ch in node.get("children", []))

        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            for q in (
                "Count(Intersect(Row(f=1), Row(f=2)))",  # batcher-led
                "TopN(f, n=2)",  # direct: tag on the api.query root
            ):
                cold = api.query_response("i", q, profile=True)
                assert not any(_tagged(r) for r in cold.profile["roots"])
                warm = api.query_response("i", q, profile=True)
                assert warm.results == cold.results
                assert any(_tagged(r) for r in warm.profile["roots"]), q

    def test_recalculate_caches_flushes(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            q = "TopN(f, n=2)"
            api.query("i", q)
            api.query("i", q)
            e0 = _snap()["entries"]
            assert e0 > 0
            api.recalculate_caches()
            assert _snap()["entries"] == 0


# ---------------------------------------------------------------------------
# incremental count repair
# ---------------------------------------------------------------------------


class TestCountRepair:
    def _setup(self, c):
        api = c[0].api
        api.create_index("i")
        api.create_field("i", "f")
        cols = np.arange(100, dtype=np.uint64)
        api.import_bits("i", "f", np.full(100, 1, np.uint64), cols)
        q = "Count(Row(f=1))"
        assert api.query("i", q)[0] == 100
        assert api.query("i", q)[0] == 100  # cached
        return api, q

    def test_set_only_burst_repairs_in_place(self):
        with _harness(1) as c:
            api, q = self._setup(c)
            # staged burst: 50 already-set + 150 new bits (overlap makes
            # popcount(delta & ~old) != popcount(delta))
            api.import_bits(
                "i", "f", np.full(200, 1, np.uint64),
                np.arange(50, 250, dtype=np.uint64),
            )
            h0, m0, p0, s0, e0, r0, u0 = _seed_counts()
            got = api.query("i", q)[0]
            h1, m1, p1, s1, e1, r1, u1 = _seed_counts()
            assert got == 250
            assert p1 - p0 == 1  # one in-place repair
            assert h1 - h0 == 1  # served from the cache
            assert m1 - m0 == 0  # a repaired serve is NOT also a miss
            assert s1 - s0 == 0  # no re-store: the entry was patched
            assert (e1 - e0, r1 - r0) == (0, 0)  # zero dispatch/read
            assert u1 - u0 == 0  # operand words never re-uploaded
            # oracle: the naive model agrees
            assert got == NaiveBitmap(range(250)).count()

    def test_burst_to_other_row_rekeys_without_recompute(self):
        with _harness(1) as c:
            api, q = self._setup(c)
            api.import_bits(
                "i", "f", np.full(80, 2, np.uint64),
                np.arange(80, dtype=np.uint64),
            )
            h0, _, p0, _, e0, _, _ = _seed_counts()
            assert api.query("i", q)[0] == 100
            h1, _, p1, _, e1, _, _ = _seed_counts()
            assert h1 - h0 == 1  # still a cache hit
            assert p1 - p0 == 0  # row untouched: re-key only, no patch
            assert e1 - e0 == 0

    def test_clear_falls_back_to_recompute(self):
        with _harness(1) as c:
            api, q = self._setup(c)
            api.import_bits(
                "i", "f", np.full(30, 1, np.uint64),
                np.arange(30, dtype=np.uint64), clear=True,
            )
            p0 = _snap()["repairs"]
            assert api.query("i", q)[0] == 70
            assert _snap()["repairs"] == p0  # non-monotone: no repair
            assert api.query("i", q)[0] == 70

    def test_mutex_writes_fall_back_to_recompute(self):
        with _harness(1) as c:
            api = c[0].api
            api.create_index("i")
            api.create_field("i", "m", {"type": "mutex"})
            cols = np.arange(50, dtype=np.uint64)
            api.import_bits("i", "m", np.full(50, 1, np.uint64), cols)
            q = "Count(Row(m=1))"
            assert api.query("i", q)[0] == 50
            assert api.query("i", q)[0] == 50
            # mutex steal: cols 0..19 move to row 2
            api.import_bits("i", "m", np.full(20, 2, np.uint64), cols[:20])
            assert api.query("i", q)[0] == 30
            assert api.query("i", "Count(Row(m=2))")[0] == 20

    def test_repair_disabled_still_correct(self):
        with _harness(1, cache_count_repair=False) as c:
            api, q = self._setup(c)
            api.import_bits(
                "i", "f", np.full(100, 1, np.uint64),
                np.arange(100, 200, dtype=np.uint64),
            )
            p0 = _snap()["repairs"]
            assert api.query("i", q)[0] == 200
            assert _snap()["repairs"] == p0
            assert api.query("i", q)[0] == 200

    def test_repeated_bursts_chain_repairs(self):
        with _harness(1) as c:
            api, q = self._setup(c)
            total = set(range(100))
            rng = np.random.default_rng(11)
            for _ in range(5):
                cols = rng.integers(0, 3 * SHARD_WIDTH, 300).astype(np.uint64)
                api.import_bits(
                    "i", "f", np.full(len(cols), 1, np.uint64), cols
                )
                total.update(int(x) for x in cols)
                assert api.query("i", q)[0] == len(total)
            assert _snap()["repairs"] >= 3


# ---------------------------------------------------------------------------
# distributed paths
# ---------------------------------------------------------------------------


class TestFanoutPath:
    def test_coordinator_caches_on_assembled_vector(self):
        with _harness(3) as c:
            api = c[0].api
            _seed(api, shards=6)
            q = "Count(Intersect(Row(f=1), Row(f=2)))"
            results = [api.query("i", q)[0] for _ in range(4)]
            assert len(set(results)) == 1
            # candidate gating: sighting 1 uncached, 2 stores, 3+ hit
            h = _snap()["hits"]
            assert h >= 1
            e0 = planmod.STATS["evals"]
            assert api.query("i", q)[0] == results[0]
            assert planmod.STATS["evals"] == e0  # hit: no dispatch anywhere

    def test_write_through_any_node_refreshes(self):
        with _harness(3) as c:
            _seed(c[0].api, shards=6)
            q = "Count(Row(f=1))"
            vals = [c[0].api.query("i", q)[0] for _ in range(3)]
            # write lands through a DIFFERENT node's api
            col = 5 * SHARD_WIDTH + 17
            c[1].api.import_bits(
                "i", "f", np.array([1], np.uint64),
                np.array([col], np.uint64),
            )
            got = c[0].api.query("i", q)[0]
            assert got == vals[0] + 1
            assert c[0].api.query("i", q)[0] == got

    def test_remote_leg_results_cache_on_the_peer(self):
        with _harness(2) as c:
            _seed(c[0].api, shards=4)
            q = "Count(Row(f=1))"
            for _ in range(3):
                c[0].api.query("i", q)
            # the peers executed legs with remote=True: their executors
            # cached the leg partials under remote-scoped keys
            assert _snap()["stores"] >= 1


class TestMeshPath:
    def test_mesh_repeats_hit_without_rpc_gating(self):
        with _harness(3, mesh_group="rc-ici") as c:
            api = c[0].api
            _seed(api, shards=6)
            q = "Count(Union(Row(f=1), Row(f=3)))"
            cold = api.query("i", q)[0]
            h0, _, _, _, e0, r0, _ = _seed_counts()
            warm = api.query("i", q)[0]
            h1, _, _, _, e1, r1, _ = _seed_counts()
            assert warm == cold
            # in-process members need no RPC: the SECOND query already
            # serves from the assembled in-process vector
            assert h1 - h0 == 1
            assert (e1 - e0, r1 - r0) == (0, 0)

    def test_member_write_invalidates_group_entry(self):
        with _harness(3, mesh_group="rc-ici2") as c:
            api = c[0].api
            _seed(api, shards=6)
            q = "Count(Row(f=1))"
            base = api.query("i", q)[0]
            assert api.query("i", q)[0] == base
            # find a column owned by a non-coordinator member and set it
            cluster = c[0].cluster
            for s in range(6):
                owner = cluster.shard_nodes("i", s)[0]
                if owner.id != c[0].node.id:
                    break
            col = s * SHARD_WIDTH + 12345
            c[1].api.import_bits(
                "i", "f", np.array([1], np.uint64),
                np.array([col], np.uint64),
            )
            got = api.query("i", q)[0]
            assert got == base + 1


# ---------------------------------------------------------------------------
# differential: cached == recomputed bit-for-bit across randomized
# mutation interleavings, naive model as the Count oracle
# ---------------------------------------------------------------------------


_DIFF_EXPRS = [
    ("Count(Row(f=1))", lambda m, ex: len(m[("f", 1)])),
    ("Count(Row(m=1))", lambda m, ex: len(m[("m", 1)])),
    (
        "Count(Intersect(Row(f=1), Row(g=1)))",
        lambda m, ex: NaiveBitmap(m[("f", 1)])
        .intersect(NaiveBitmap(m[("g", 1)]))
        .count(),
    ),
    (
        "Count(Union(Row(f=0), Row(g=2)))",
        lambda m, ex: NaiveBitmap(m[("f", 0)])
        .union(NaiveBitmap(m[("g", 2)]))
        .count(),
    ),
    (
        "Count(Difference(Row(f=1), Row(g=0)))",
        lambda m, ex: NaiveBitmap(m[("f", 1)])
        .difference(NaiveBitmap(m[("g", 0)]))
        .count(),
    ),
    (
        "Count(Xor(Row(f=2), Row(g=2)))",
        lambda m, ex: NaiveBitmap(m[("f", 2)])
        .xor(NaiveBitmap(m[("g", 2)]))
        .count(),
    ),
    (
        "Count(Not(Row(f=1)))",
        lambda m, ex: len(ex - m[("f", 1)]),
    ),
]
_DIFF_RECOMPUTE_ONLY = ["TopN(f, n=3)", "GroupBy(Rows(f), Rows(g))"]


class TestDifferential:
    @pytest.mark.parametrize("mode", ["single", "fanout", "mesh"])
    def test_cached_equals_recomputed_under_mutations(self, mode, rng):
        n = 1 if mode == "single" else 3
        kw = {"mesh_group": "dif-ici"} if mode == "mesh" else {}
        n_shards = 3
        with _harness(n, **kw) as c:
            api = c[0].api
            api.create_index("d")
            for fname in ("f", "g"):
                api.create_field("d", fname)
            api.create_field("d", "m", {"type": "mutex"})
            model = {
                (fl, r): set() for fl in ("f", "g", "m") for r in range(3)
            }
            mutex_owner: dict = {}
            existence: set = set()

            def do_import(fl, clear=False):
                r = int(rng.integers(0, 3))
                cols = np.unique(
                    rng.integers(0, n_shards * SHARD_WIDTH, 120)
                ).astype(np.uint64)
                node = c[int(rng.integers(0, n))]
                node.api.import_bits(
                    "d", fl, np.full(len(cols), r, np.uint64), cols,
                    clear=clear,
                )
                existence.update(int(x) for x in cols)
                if fl == "m":
                    for col in (int(x) for x in cols):
                        old = mutex_owner.get(col)
                        if old is not None:
                            model[("m", old)].discard(col)
                        mutex_owner[col] = r
                        model[("m", r)].add(col)
                elif clear:
                    model[(fl, r)].difference_update(int(x) for x in cols)
                else:
                    model[(fl, r)].update(int(x) for x in cols)

            def check_query():
                pql, expect = _DIFF_EXPRS[
                    int(rng.integers(0, len(_DIFF_EXPRS)))
                ]
                node = c[int(rng.integers(0, n))]
                want = expect(model, existence)
                got = node.api.query("d", pql)[0]
                assert got == want, (pql, got, want)
                # repeat immediately: the cached answer must agree
                assert node.api.query("d", pql)[0] == want, pql

            do_import("f")
            do_import("g")
            do_import("m")
            for _ in range(40):
                roll = rng.random()
                if roll < 0.25:
                    do_import("f")
                elif roll < 0.4:
                    do_import("g")
                elif roll < 0.5:
                    do_import("m")
                elif roll < 0.6:
                    do_import("f", clear=True)
                else:
                    check_query()
            # final sweep: every expression, cached vs naive vs a fresh
            # recompute with the cache dropped
            for pql, expect in _DIFF_EXPRS:
                want = expect(model, existence)
                cached = api.query("d", pql)[0]
                assert cached == want, (pql, cached, want)
            for pql in _DIFF_RECOMPUTE_ONLY:
                cached = api.query("d", pql)
                cached2 = api.query("d", pql)
                RESULT_CACHE.reset()
                fresh = api.query("d", pql)
                assert cached == cached2 == fresh, pql


# ---------------------------------------------------------------------------
# GC + cost discount + concurrency
# ---------------------------------------------------------------------------


class TestClockFastPath:
    """The O(#views) revalidation fast path (View.mutation_clock):
    sound only if EVERY mutation funnel that bumps a fragment version
    also bumps its view's clock — probe each funnel and assert both the
    bump and post-mutation correctness."""

    def test_every_mutation_funnel_bumps_the_clock(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            v = c[0].holder.index("i").field("f").view("standard")
            q = "Count(Row(f=1))"

            def served():
                api.query("i", q)
                return api.query("i", q)[0]

            base = served()
            mutations = [
                # staged bulk router (stage_bulk, notify=False path)
                lambda: api.import_bits(
                    "i", "f", np.array([1], np.uint64),
                    np.array([SHARD_WIDTH + 1], np.uint64),
                ),
                # exact clear import (import_positions funnel)
                lambda: api.import_bits(
                    "i", "f", np.array([1], np.uint64),
                    np.array([SHARD_WIDTH + 1], np.uint64), clear=True,
                ),
                # single-bit PQL writes (set_bit/clear_bit funnels)
                lambda: api.query("i", f"Set({SHARD_WIDTH + 2}, f=1)"),
                lambda: api.query("i", f"Clear({SHARD_WIDTH + 2}, f=1)"),
            ]
            expect = base
            deltas = [1, -1, 1, -1]
            for mutate, d in zip(mutations, deltas):
                clock0 = v.mutation_clock
                mutate()
                assert v.mutation_clock > clock0, mutate
                expect += d
                assert served() == expect

    def test_exact_revalidation_rearms_the_clock(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            api.create_field("i", "g")
            api.import_bits(
                "i", "g", np.full(8, 1, np.uint64),
                np.arange(8, dtype=np.uint64),
            )
            q = "Count(Row(f=1))"
            api.query("i", q)
            api.query("i", q)
            (entry,) = RESULT_CACHE._entries.values()
            assert entry.clocks is not None  # armed at store
            # a write to ANOTHER FIELD's view leaves f's clock alone:
            # the repeat stays on the fast path
            api.import_bits(
                "i", "g", np.array([1], np.uint64),
                np.array([9], np.uint64),
            )
            h0 = _snap()["hits"]
            api.query("i", q)
            assert _snap()["hits"] == h0 + 1
            assert entry.clocks is not None


class TestGC:
    def test_index_churn_returns_cache_to_baseline(self):
        with _harness(1) as c:
            srv = c[0]
            base_bytes = _snap()["resident_bytes"]
            for i in range(20):
                name = f"churn_{i}"
                _seed(srv.api, index=name, n=30, shards=1)
                srv.api.query(name, "Count(Row(f=1))")
                srv.api.query(name, "Count(Row(f=1))")  # stores + hits
                assert _snap()["by_index"].get(name, 0) > 0
                srv.api.delete_index(name)
                assert name not in _snap()["by_index"]
            snap = _snap()
            assert snap["resident_bytes"] == base_bytes
            assert not any(k.startswith("churn_") for k in snap["by_index"])

    def test_field_delete_drops_covering_entries(self):
        with _harness(1) as c:
            api = c[0].api
            _seed(api)
            q = "Count(Row(f=1))"
            api.query("i", q)
            api.query("i", q)
            assert _snap()["entries"] > 0
            api.delete_field("i", "f")
            assert _snap()["entries"] == 0


class TestCostDiscount:
    def test_cache_hit_likely_queries_admit_byte_free(self):
        from pilosa_tpu.pql import parse
        from pilosa_tpu.sched import cost as costmod

        with _harness(1) as c:
            api = c[0].api
            _seed(api, n=500, shards=4)
            api.create_field("i", "g")
            api.import_bits(
                "i", "g", np.full(64, 1, np.uint64),
                np.arange(64, dtype=np.uint64),
            )
            idx = c[0].holder.index("i")
            q = parse("Count(Row(f=1))")
            cold = costmod.estimate(idx, q)
            assert cold.device_bytes > 0
            api.query("i", "Count(Row(f=1))")  # stores the entry
            warm = costmod.estimate(idx, q)
            assert warm.device_bytes == 0
            # no text aliasing: an uncached query over an un-staged field
            # keeps its full admission weight
            other = costmod.estimate(idx, parse("Count(Row(g=1))"))
            assert other.device_bytes > 0
            # a covered mutation makes the entry maybe-stale: its repeat
            # may recompute at full cost, so the discount must NOT let
            # it bypass the byte budget (the staged surcharge applies)
            api.import_bits(
                "i", "f", np.array([1], np.uint64),
                np.array([3], np.uint64),
            )
            stale = costmod.estimate(idx, q)
            assert stale.device_bytes > 0
            # a served repeat (repair or recompute+restore) proves the
            # entry fresh again and re-arms the discount
            api.query("i", "Count(Row(f=1))")
            again = costmod.estimate(idx, q)
            assert again.device_bytes == 0

    def test_discount_resolves_row_keys_read_only(self):
        """Admission sees PRE-translation text but entries key on
        translated text: the probe resolves row keys via find_key —
        read-only, never minting ids — so keyed-field repeats still
        admit byte-free."""
        from pilosa_tpu.pql import parse
        from pilosa_tpu.sched import cost as costmod

        with _harness(1) as c:
            api = c[0].api
            api.create_index("k")
            api.create_field("k", "f", {"keys": True})
            api.import_bits(
                "k", "f", ["alpha"] * 30, np.arange(30, dtype=np.uint64)
            )
            idx = c[0].holder.index("k")
            f = idx.field("f")
            api.query("k", 'Count(Row(f="alpha"))')  # stores (translated)
            warm = costmod.estimate(idx, parse('Count(Row(f="alpha"))'))
            assert warm.device_bytes == 0
            # unknown key: no discount decision may CREATE the id
            costmod.estimate(idx, parse('Count(Row(f="nope"))'))
            assert f.translate_store.find_key("nope") is None


class TestConcurrency:
    def test_readers_race_staged_writers_stay_exact(self):
        with _harness(1) as c:
            api = c[0].api
            api.create_index("i")
            api.create_field("i", "f")
            api.import_bits(
                "i", "f", np.full(100, 1, np.uint64),
                np.arange(100, dtype=np.uint64),
            )
            stop = threading.Event()
            errors: list = []
            written: set = set(range(100))
            lock = threading.Lock()

            def writer():
                rng = np.random.default_rng(5)
                while not stop.is_set():
                    cols = rng.integers(0, 2 * SHARD_WIDTH, 50).astype(
                        np.uint64
                    )
                    with lock:
                        api.import_bits(
                            "i", "f", np.full(50, 1, np.uint64), cols
                        )
                        written.update(int(x) for x in cols)

            def reader():
                try:
                    while not stop.is_set():
                        with lock:
                            want = len(written)
                            got = api.query("i", "Count(Row(f=1))")[0]
                        if got != want:
                            errors.append((got, want))
                            return
                except Exception as e:  # noqa: BLE001 - surfaced below
                    errors.append(repr(e))

            threads = [threading.Thread(target=writer)] + [
                threading.Thread(target=reader) for _ in range(3)
            ]
            for t in threads:
                t.start()
            import time

            time.sleep(1.0)
            stop.set()
            for t in threads:
                t.join(10)
            assert not errors, errors[:3]
            assert api.query("i", "Count(Row(f=1))")[0] == len(written)
