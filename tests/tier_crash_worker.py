"""Tier kill-matrix worker: a real OS process SIGKILLed inside the
demote/hydrate protocol windows.

Launched by tests/test_tier_faults.py (NOT collected by pytest). The
worker imports a deterministic corpus into an on-disk holder, then
drives the tier protocol with a FaultInjector "kill" store rule armed
at one exact protocol point:

  tier.demote.pre_delete — the object is uploaded durably and the key
      registered cold, but the LOCAL COPY IS STILL ON DISK. A restart
      must reopen the fragment locally (the cold scan skips keys with
      local copies) bit-identically — the stale object is harmless.

  tier.hydrate.pre_apply — the object is fetched but NOTHING local
      exists yet. A restart must find the key still cold and a fresh
      hydration must converge bit-identically.

All imports are acked (returned) before the kill window opens, so the
parent's bit-identity assertion doubles as "no acked write lost".
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SHARDS = 2


def corpus_bits():
    """Deterministic corpus the parent regenerates to audit the
    survivor state."""
    import numpy as np

    from pilosa_tpu.shardwidth import SHARD_WIDTH

    rng = np.random.default_rng(777)
    n = 300
    rows = rng.integers(0, 4, n).astype(np.uint64)
    cols = rng.integers(0, N_SHARDS * SHARD_WIDTH, n).astype(np.uint64)
    return rows, cols


def open_tiered(data_dir, store_dir):
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.tier import TierManager, TierPolicy
    from pilosa_tpu.tier.store import LocalDirStore

    h = Holder(data_dir).open()
    idx = h.create_index_if_not_exists("tc")
    f = idx.create_field_if_not_exists("f", FieldOptions())
    tier = TierManager(
        LocalDirStore(store_dir), TierPolicy("cold"), h
    )
    return h, f, tier


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--point", required=True,
                    choices=["tier.demote.pre_delete",
                             "tier.hydrate.pre_apply"])
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--store-dir", required=True)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from pilosa_tpu.server import faults

    h, f, tier = open_tiered(args.data_dir, args.store_dir)
    rows, cols = corpus_bits()
    f.import_bits(rows, cols)  # fully acked before any kill window
    v = f.views["standard"]
    print("IMPORTED", flush=True)

    if args.point == "tier.hydrate.pre_apply":
        # demote CLEANLY first; the kill targets the hydrate that follows
        for shard in sorted(v.fragments):
            assert tier.demote_fragment(v, v.fragments[shard]), shard
        print("DEMOTED", flush=True)

    inj = faults.FaultInjector(seed=0)
    inj.add_store_rule("kill", point=args.point)
    faults.install_injector(inj)

    if args.point == "tier.demote.pre_delete":
        # dies between "object durable + key cold" and "local delete"
        shard = sorted(v.fragments)[0]
        tier.demote_fragment(v, v.fragments[shard])
    else:
        # dies between "object fetched" and "anything local written"
        tier.hydrate(v, 0)

    print("COMPLETED", flush=True)  # the kill point never fired
    faults.uninstall_injector()
    h.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
