"""TLS for the server plane + internode client (VERDICT r4 #4).

Reference: server/config.go:151-157 (TLS block) applied in
server.go:222-295 — one cert/key pair serves the client API and internode
traffic; the internode client carries skip-verify / CA trust config.
Certs are self-signed per test session via the openssl CLI."""

import json
import ssl
import subprocess
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.server.client import ClientError, InternalClient
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    import shutil

    if shutil.which("openssl") is None:
        pytest.skip("openssl binary not available for cert generation")
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "node.crt"), str(d / "node.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "2", "-nodes",
            "-subj", "/CN=localhost",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def _https_get(url, cafile=None):
    if cafile:
        ctx = ssl.create_default_context(cafile=cafile)
    else:
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    with urllib.request.urlopen(url, context=ctx, timeout=10) as r:
        return json.loads(r.read())


class TestSingleNode:
    def test_serves_https(self, certs):
        cert, key = certs
        srv = NodeServer(None, "tls1", tls_cert=cert, tls_key=key)
        srv.start()
        try:
            assert srv.node.uri.startswith("https://")
            status = _https_get(srv.node.uri + "/status")
            assert status["state"] == "NORMAL"
            # the advertised URI must be the one that actually serves TLS
            status2 = _https_get(srv.node.uri + "/status", cafile=cert)
            assert status2["nodes"][0]["uri"] == srv.node.uri
        finally:
            srv.stop()

    def test_plain_http_rejected(self, certs):
        cert, key = certs
        srv = NodeServer(None, "tls2", tls_cert=cert, tls_key=key)
        srv.start()
        try:
            url = srv.node.uri.replace("https://", "http://") + "/status"
            with pytest.raises(Exception):
                urllib.request.urlopen(url, timeout=5)
        finally:
            srv.stop()

    def test_cert_without_key_rejected(self, certs):
        cert, _ = certs
        with pytest.raises(ValueError):
            NodeServer(None, "tls3", tls_cert=cert)

    def test_client_verifies_against_ca(self, certs):
        cert, key = certs
        srv = NodeServer(None, "tls4", tls_cert=cert, tls_key=key)
        srv.start()
        try:
            pinned = InternalClient(tls_ca_cert=cert)
            assert pinned.status(srv.node.uri)["state"] == "NORMAL"
            # default trust store does NOT contain our self-signed cert
            strict = InternalClient()
            with pytest.raises(ClientError):
                strict.status(srv.node.uri)
        finally:
            srv.stop()


class TestHostScheme:
    def test_parse_hosts_tls_scheme(self):
        """Bare --cluster-hosts entries must seed https:// URIs on a TLS
        cluster, or every internode request would send plaintext to a TLS
        socket (code-review r5 finding)."""
        from pilosa_tpu.cli.config import parse_hosts

        plain = parse_hosts(["a:1", "n2@b:2", "n3@http://c:3"])
        assert plain == [
            ("a-1", "http://a:1"), ("n2", "http://b:2"), ("n3", "http://c:3")
        ]
        tls = parse_hosts(
            ["a:1", "n2@b:2", "n3@https://c:3"], default_scheme="https"
        )
        assert tls == [
            ("a-1", "https://a:1"), ("n2", "https://b:2"), ("n3", "https://c:3")
        ]


class TestTLSCluster:
    def test_three_node_cluster_over_tls(self, certs):
        """Full cluster plane over TLS: DDL broadcast, distributed write +
        query fan-out, TopN — every internode hop is HTTPS."""
        with ClusterHarness(3, in_memory=True, tls=certs) as cluster:
            for srv in cluster.nodes:
                assert srv.node.uri.startswith("https://")
            api = cluster[0].api
            api.create_index("ti")
            api.create_field("ti", "f")
            rng = np.random.default_rng(4)
            # spread bits across enough shards that every node owns some
            cols = rng.integers(0, 6 * SHARD_WIDTH, 4000).astype(np.uint64)
            q = "".join(f"Set({int(c)}, f=1)" for c in cols[:300])
            api.query("ti", q)
            expect = len({int(c) for c in cols[:300]})
            # count from EVERY node: remote fan-out goes over TLS
            for srv in cluster.nodes:
                (got,) = srv.api.query("ti", "Count(Row(f=1))")
                assert got == expect
            (top,) = cluster[1].api.query("ti", "TopN(f, n=1)")
            assert top[0].id == 1 and top[0].count == expect
