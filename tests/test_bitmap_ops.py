"""Differential tests: device bitmap engine vs the naive set-model oracle.

Mirrors the reference's fuzz/differential strategy (roaring/fuzzer.go:37
FuzzRoaringOps against roaring/naive.go)."""

import numpy as np
import pytest

from pilosa_tpu.core.naive import NaiveBitmap
from pilosa_tpu.ops import bitmap as ob

N_BITS = 1 << 16  # small shard width for tests; ops are width-polymorphic
N_WORDS = N_BITS // 32


def rand_positions(rng, n, lo=0, hi=N_BITS):
    return np.unique(rng.integers(lo, hi, size=n))


def pack(positions):
    return ob.pack_positions(positions, N_BITS)


class TestPacking:
    def test_roundtrip(self, rng):
        pos = rand_positions(rng, 1000)
        words = pack(pos)
        assert np.array_equal(ob.unpack_positions(words), pos.astype(np.uint64))

    def test_empty(self):
        words = pack([])
        assert words.shape == (N_WORDS,)
        assert ob.unpack_positions(words).size == 0

    def test_boundaries(self):
        for p in [0, 31, 32, 33, 63, 64, N_BITS - 1]:
            words = pack([p])
            assert list(ob.unpack_positions(words)) == [p]

    def test_out_of_range_raises(self):
        with pytest.raises(ValueError):
            pack([N_BITS])


class TestAlgebra:
    def setup_method(self, method):
        rng = np.random.default_rng(7)
        self.pa = rand_positions(rng, 2000)
        self.pb = rand_positions(rng, 3000)
        self.na = NaiveBitmap(self.pa.tolist())
        self.nb = NaiveBitmap(self.pb.tolist())
        self.wa = pack(self.pa)
        self.wb = pack(self.pb)

    def check(self, device_words, naive):
        got = ob.unpack_positions(np.asarray(device_words))
        assert got.tolist() == naive.slice()

    def test_and(self):
        self.check(ob.b_and(self.wa, self.wb), self.na.intersect(self.nb))

    def test_or(self):
        self.check(ob.b_or(self.wa, self.wb), self.na.union(self.nb))

    def test_xor(self):
        self.check(ob.b_xor(self.wa, self.wb), self.na.xor(self.nb))

    def test_andnot(self):
        self.check(ob.b_andnot(self.wa, self.wb), self.na.difference(self.nb))

    def test_not_bounded_by_exists(self):
        exists = self.wb  # treat b as the existence row
        self.check(ob.b_not(self.wa, exists), self.nb.difference(self.na))

    def test_popcount(self):
        assert int(ob.popcount(self.wa)) == self.na.count()

    def test_count_and_fused(self):
        assert int(ob.count_and(self.wa, self.wb)) == self.na.intersection_count(self.nb)

    def test_count_andnot(self):
        assert int(ob.count_andnot(self.wa, self.wb)) == self.na.difference(self.nb).count()

    def test_union_reduce(self):
        rng = np.random.default_rng(3)
        stacks, naives = [], []
        for _ in range(5):
            p = rand_positions(rng, 500)
            stacks.append(pack(p))
            naives.append(NaiveBitmap(p.tolist()))
        out = ob.union_reduce(np.stack(stacks))
        self.check(out, naives[0].union(*naives[1:]))

    def test_intersect_reduce(self):
        rng = np.random.default_rng(4)
        base = rand_positions(rng, 30000)
        stacks = [pack(base)]
        naive = NaiveBitmap(base.tolist())
        for _ in range(3):
            p = rand_positions(rng, 30000)
            stacks.append(pack(p))
            naive = naive.intersect(NaiveBitmap(p.tolist()))
        self.check(ob.intersect_reduce(np.stack(stacks)), naive)

    def test_xor_reduce(self):
        out = ob.xor_reduce(np.stack([self.wa, self.wb, self.wa]))
        self.check(out, self.na.xor(self.nb).xor(self.na))

    def test_popcount_rows_batched(self):
        stack = np.stack([self.wa, self.wb])
        counts = np.asarray(ob.popcount_rows(stack))
        assert counts.tolist() == [self.na.count(), self.nb.count()]


class TestRangeAndShift:
    def test_range_mask(self):
        for start, stop in [(0, 0), (0, 1), (5, 37), (0, N_BITS), (100, 100), (31, 33), (64, 96)]:
            mask = np.asarray(ob.range_mask_words(start, stop, N_BITS))
            expect = NaiveBitmap(range(start, stop))
            assert ob.unpack_positions(mask).tolist() == expect.slice()

    def test_count_range(self, rng):
        pos = rand_positions(rng, 5000)
        naive = NaiveBitmap(pos.tolist())
        words = pack(pos)
        for start, stop in [(0, N_BITS), (100, 1000), (0, 1), (N_BITS - 10, N_BITS)]:
            assert int(ob.count_range(words, start, stop)) == naive.count_range(start, stop)

    @pytest.mark.parametrize("n", [1, 5, 32, 33, 64, 100])
    def test_shift_with_overflow(self, rng, n):
        pos = rand_positions(rng, 3000)
        naive = NaiveBitmap(pos.tolist())
        words = pack(pos)
        shifted, overflow = ob.shift_bits(words, n)
        shifted_naive = naive.shift(n)
        in_shard = NaiveBitmap([p for p in shifted_naive.slice() if p < N_BITS])
        carried = NaiveBitmap([p - N_BITS for p in shifted_naive.slice() if p >= N_BITS])
        assert ob.unpack_positions(np.asarray(shifted)).tolist() == in_shard.slice()
        assert ob.unpack_positions(np.asarray(overflow)).tolist() == carried.slice()

    def test_shift_zero(self, rng):
        pos = rand_positions(rng, 100)
        words = pack(pos)
        shifted, overflow = ob.shift_bits(words, 0)
        assert np.array_equal(np.asarray(shifted), words)
        assert int(ob.popcount(np.asarray(overflow))) == 0


class TestNaiveModel:
    """Validate the oracle itself (reference: roaring/naive_test.go)."""

    def test_basic(self):
        b = NaiveBitmap()
        assert b.add(1, 5, 100)
        assert not b.add(1)
        assert b.contains(5)
        assert not b.contains(6)
        assert b.count() == 3
        assert b.remove(5)
        assert not b.remove(5)
        assert b.slice() == [1, 100]

    def test_flip(self):
        b = NaiveBitmap([1, 3])
        assert b.flip(1, 4).slice() == [2, 4]

    def test_offset_range(self):
        b = NaiveBitmap([10, 20, 300])
        assert b.offset_range(1000, 0, 256).slice() == [1010, 1020]
