"""PQL grammar corpus tests (reference: pql/pqlpeg_test.go patterns)."""

import pytest

from pilosa_tpu.pql import Call, Condition, ParseError, parse


def one(src):
    q = parse(src)
    assert len(q.calls) == 1
    return q.calls[0]


class TestBasicCalls:
    def test_set(self):
        c = one("Set(1, f=2)")
        assert c.name == "Set"
        assert c.args == {"_col": 1, "f": 2}

    def test_set_string_keys(self):
        c = one('Set("col-key", f="row-key")')
        assert c.args == {"_col": "col-key", "f": "row-key"}

    def test_set_with_timestamp(self):
        c = one("Set(1, f=2, 2019-07-04T12:00)")
        assert c.args["_timestamp"] == "2019-07-04T12:00"

    def test_set_with_quoted_timestamp(self):
        c = one("Set(1, f=2, '2019-07-04T12:00')")
        assert c.args["_timestamp"] == "2019-07-04T12:00"

    def test_row(self):
        c = one("Row(f=5)")
        assert c.name == "Row" and c.args == {"f": 5}

    def test_row_key(self):
        assert one("Row(f=abcd)").args == {"f": "abcd"}

    def test_clear(self):
        assert one("Clear(3, f=1)").args == {"_col": 3, "f": 1}

    def test_clear_row(self):
        assert one("ClearRow(f=5)").args == {"f": 5}

    def test_store(self):
        c = one("Store(Row(f=10), f=20)")
        assert c.name == "Store"
        assert c.children[0].name == "Row"
        assert c.args == {"f": 20}

    def test_multiple_calls(self):
        q = parse("Set(1, f=2) Set(3, f=4)\nCount(Row(f=2))")
        assert [c.name for c in q.calls] == ["Set", "Set", "Count"]
        assert q.write_call_n() == 2


class TestNestedCalls:
    def test_intersect(self):
        c = one("Intersect(Row(a=1), Row(b=2))")
        assert c.name == "Intersect"
        assert [ch.name for ch in c.children] == ["Row", "Row"]
        assert c.children[0].args == {"a": 1}

    def test_deep_nesting(self):
        c = one("Count(Union(Intersect(Row(a=1), Row(b=2)), Not(Row(c=3))))")
        assert c.name == "Count"
        u = c.children[0]
        assert [ch.name for ch in u.children] == ["Intersect", "Not"]

    def test_call_and_args_mix(self):
        c = one("Shift(Row(f=1), n=3)")
        assert c.children[0].name == "Row"
        assert c.args == {"n": 3}

    def test_call_as_arg_value(self):
        c = one("Sum(filter=Row(a=1), field=f)")
        assert isinstance(c.args["filter"], Call)
        assert c.args["filter"].name == "Row"
        assert c.args["field"] == "f"
        assert c.children == []


class TestTopNRows:
    def test_topn_bare(self):
        c = one("TopN(f)")
        assert c.args == {"_field": "f"}

    def test_topn_n(self):
        c = one("TopN(f, n=5)")
        assert c.args == {"_field": "f", "n": 5}

    def test_topn_with_filter_child(self):
        c = one("TopN(f, Row(other=1), n=5)")
        assert c.children[0].name == "Row"
        assert c.args["n"] == 5

    def test_topn_attr_values(self):
        c = one('TopN(f, n=2, attrName="category", attrValues=[1, 2, 3])')
        assert c.args["attrValues"] == [1, 2, 3]

    def test_rows(self):
        c = one("Rows(f, limit=10, previous=3, column=5)")
        assert c.args == {"_field": "f", "limit": 10, "previous": 3, "column": 5}

    def test_groupby(self):
        c = one("GroupBy(Rows(a), Rows(b), limit=10, filter=Row(c=1))")
        assert [ch.name for ch in c.children] == ["Rows", "Rows"]
        assert c.args["limit"] == 10
        assert isinstance(c.args["filter"], Call)


class TestConditions:
    def test_gt(self):
        c = one("Row(f > 5)")
        assert isinstance(c.args["f"], Condition)
        assert c.args["f"].op == ">" and c.args["f"].value == 5

    @pytest.mark.parametrize("op", ["<", ">", "<=", ">=", "==", "!="])
    def test_all_ops(self, op):
        c = one(f"Row(f {op} 5)")
        assert c.args["f"].op == op

    def test_neq_null(self):
        c = one("Row(f != null)")
        assert c.args["f"].op == "!=" and c.args["f"].value is None

    def test_between_conditional(self):
        c = one("Row(5 < f < 10)")
        assert c.args["f"].op == "><"
        assert c.args["f"].value == [6, 9]  # strict bounds shifted inward

    def test_between_conditional_lte(self):
        c = one("Row(5 <= f <= 10)")
        assert c.args["f"].value == [5, 10]

    def test_between_brackets(self):
        c = one("Row(f >< [5, 10])")
        assert c.args["f"].op == "><" and c.args["f"].value == [5, 10]

    def test_negative_predicate(self):
        c = one("Row(f > -10)")
        assert c.args["f"].value == -10


class TestRange:
    def test_range_time(self):
        c = one("Range(f=1, from='2010-01-01T00:00', to='2011-01-01T00:00')")
        assert c.name == "Range"
        assert c.args == {
            "f": 1,
            "from": "2010-01-01T00:00",
            "to": "2011-01-01T00:00",
        }

    def test_range_no_keywords(self):
        c = one("Range(f=1, 2010-01-01T00:00, 2011-01-01T00:00)")
        assert c.args["from"] == "2010-01-01T00:00"

    def test_range_cond_fallback(self):
        c = one("Range(f > 5)")
        assert c.args["f"].op == ">"

    def test_row_time_range(self):
        c = one("Row(f=1, from='2010-01-01T00:00', to='2011-01-01T00:00')")
        assert c.args["from"] == "2010-01-01T00:00"


class TestAttrs:
    def test_set_row_attrs(self):
        c = one('SetRowAttrs(f, 1, a=1, b="x", c=true, d=null)')
        assert c.args == {"_field": "f", "_row": 1, "a": 1, "b": "x", "c": True, "d": None}

    def test_set_column_attrs(self):
        c = one("SetColumnAttrs(1, a=1.5, b=false)")
        assert c.args == {"_col": 1, "a": 1.5, "b": False}

    def test_set_row_attrs_string_row(self):
        c = one('SetRowAttrs(f, "rowkey", x=1)')
        assert c.args["_row"] == "rowkey"


class TestValues:
    def test_float(self):
        assert one("F(x=1.5)").args["x"] == 1.5

    def test_leading_dot_float(self):
        assert one("F(x=.5)").args["x"] == 0.5

    def test_negative(self):
        assert one("F(x=-42)").args["x"] == -42

    def test_bools_null(self):
        assert one("F(a=true, b=false, c=null)").args == {"a": True, "b": False, "c": None}

    def test_list(self):
        assert one("F(x=[1, two, 3.5])").args["x"] == [1, "two", 3.5]

    def test_quoted_strings(self):
        assert one('F(x="hello world")').args["x"] == "hello world"
        assert one("F(x='sq')").args["x"] == "sq"

    def test_escaped_quotes(self):
        assert one('F(x="he said \\"hi\\"")').args["x"] == 'he said "hi"'

    def test_bare_string_with_specials(self):
        assert one("F(x=ab-cd_ef:1)").args["x"] == "ab-cd_ef:1"

    def test_options_shards(self):
        c = one("Options(Row(f=1), excludeColumns=true, shards=[0, 2])")
        assert c.children[0].name == "Row"
        assert c.args == {"excludeColumns": True, "shards": [0, 2]}


class TestErrors:
    @pytest.mark.parametrize("bad", [
        "Set(1, f=2",            # unbalanced
        "Row(f=)",               # missing value
        "Row(=5)",               # missing field
        "Set(1, f=2))",          # trailing garbage
        "Row(f ~ 5)",            # bad operator
        "(Row(f=1))",            # no call name
    ])
    def test_syntax_errors(self, bad):
        with pytest.raises(ParseError):
            parse(bad)

    def test_duplicate_arg(self):
        with pytest.raises(ParseError, match="duplicate"):
            parse("Row(f=1, f=2)")

    def test_empty_query(self):
        assert parse("").calls == []
        assert parse("   \n\t ").calls == []

    def test_error_reports_position(self):
        with pytest.raises(ParseError, match="line 1"):
            parse("Row(f=1) garbage")


class TestStringification:
    @pytest.mark.parametrize("src", [
        "Row(f=5)",
        "Intersect(Row(a=1), Row(b=2))",
        "TopN(f, n=5)",
    ])
    def test_roundtrip(self, src):
        q = parse(src)
        q2 = parse(str(q))
        assert str(q2) == str(q)
