"""Generate the checked-in roaring-decoder crasher corpus.

The reference checks confirmed unmarshal crashers into its repo
(/root/reference/roaring/fuzz_test.go:21-76); this is our analog, seeded
with the same failure classes (malformed headers, overrunning containers,
non-increasing keys, truncations) against BOTH decoders — the numpy codec
(core/roaring_io.py) and the C++ codec (native/roaring_codec.cpp).

Run `python tests/corpus/make_roaring_corpus.py` to (re)generate
tests/corpus/roaring/*.bin deterministically. Files prefixed `ok_` must
decode successfully (and identically in both decoders); `bad_` files must
raise RoaringError in both — never crash, hang, or return garbage.
"""

import os
import struct

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "roaring")


def pilosa_header(n_keys: int, flags: int = 0, version: int = 0) -> bytes:
    return struct.pack("<HBBI", 12348, version, flags, n_keys)


def pilosa_file(containers):
    """containers: list of (key, ctype, card, payload_bytes)."""
    n = len(containers)
    hdr = pilosa_header(n)
    desc = b"".join(
        struct.pack("<QHH", key, ctype, card - 1)
        for key, ctype, card, _ in containers
    )
    data_start = 8 + 12 * n + 4 * n
    offs, payloads, pos = [], [], data_start
    for _, _, _, payload in containers:
        offs.append(struct.pack("<I", pos))
        payloads.append(payload)
        pos += len(payload)
    return hdr + desc + b"".join(offs) + b"".join(payloads)


def array_payload(vals):
    return np.asarray(vals, dtype="<u2").tobytes()


def bitmap_payload(lows):
    bits = np.zeros(1 << 16, np.uint8)
    bits[np.asarray(lows)] = 1
    return np.packbits(bits, bitorder="little").tobytes()


def run_payload(pairs):  # (start, last) pairs, pilosa dialect
    out = struct.pack("<H", len(pairs))
    for s, l in pairs:
        out += struct.pack("<HH", s, l)
    return out


def official_norun(containers):
    """containers: list of (key, sorted_lows). Array/bitmap by cardinality."""
    n = len(containers)
    out = struct.pack("<II", 12346, n)
    descs, bodies = [], []
    pos = 8 + 4 * n + 4 * n
    for key, lows in containers:
        card = len(lows)
        descs.append(struct.pack("<HH", key, card - 1))
        body = (
            array_payload(lows) if card <= 4096 else bitmap_payload(lows)
        )
        bodies.append((pos, body))
        pos += len(body)
    offs = b"".join(struct.pack("<I", p) for p, _ in bodies)
    return out + b"".join(descs) + offs + b"".join(b for _, b in bodies)


def main():
    os.makedirs(OUT, exist_ok=True)
    cases = {}

    # ---- valid files (differential: both decoders must agree) ----
    cases["ok_empty_zero_keys.bin"] = pilosa_file([])
    cases["ok_mixed_types.bin"] = pilosa_file(
        [
            (0, 1, 3, array_payload([1, 5, 9])),
            (2, 2, 5000, bitmap_payload(list(range(5000)))),
            (7, 3, 20, run_payload([(10, 19), (100, 109)])),
        ]
    )
    cases["ok_oplog_tail_ignored.bin"] = (
        pilosa_file([(1, 1, 2, array_payload([7, 8]))]) + b"\x13\x07junk-oplog"
    )
    cases["ok_official_norun.bin"] = official_norun(
        [(0, list(range(10))), (3, list(range(0, 60000, 7)))]
    )
    cases["ok_key_above_2e16.bin"] = pilosa_file(
        [(1 << 40, 1, 2, array_payload([0, 65535]))]
    )

    # ---- malformed headers ----
    cases["bad_empty_file.bin"] = b""
    cases["bad_short_header.bin"] = b"\x3c\x30\x00"
    cases["bad_unknown_cookie.bin"] = struct.pack("<II", 99, 1)
    cases["bad_version.bin"] = struct.pack("<HBBI", 12348, 9, 0, 0)
    cases["bad_huge_n_keys.bin"] = pilosa_header(0xFFFFFFFF)
    cases["bad_header_overrun.bin"] = pilosa_header(4) + b"\x00" * 10

    # ---- key ordering ----
    good = [(5, 1, 2, array_payload([1, 2])), (3, 1, 2, array_payload([4, 5]))]
    cases["bad_nonincreasing_keys.bin"] = pilosa_file(good)
    dup = [(5, 1, 2, array_payload([1, 2])), (5, 1, 2, array_payload([4, 5]))]
    cases["bad_duplicate_keys.bin"] = pilosa_file(dup)

    # ---- overrunning containers ----
    f = bytearray(pilosa_file([(0, 1, 100, array_payload([1, 2]))]))
    cases["bad_array_overrun.bin"] = bytes(f)
    f = bytearray(pilosa_file([(0, 2, 5000, b"\x00" * 100)]))
    cases["bad_bitmap_overrun.bin"] = bytes(f)
    f = bytearray(pilosa_file([(0, 3, 10, struct.pack("<H", 500))]))
    cases["bad_run_count_overrun.bin"] = bytes(f)
    cases["bad_run_bounds.bin"] = pilosa_file(
        [(0, 3, 10, run_payload([(50, 10)]))]  # last < start
    )
    cases["bad_container_type.bin"] = pilosa_file(
        [(0, 9, 2, array_payload([1, 2]))]
    )
    # offset table pointing past the buffer
    body = bytearray(pilosa_file([(0, 1, 2, array_payload([1, 2]))]))
    struct.pack_into("<I", body, 8 + 12, 0xFFFFFF)
    cases["bad_offset_past_end.bin"] = bytes(body)

    # ---- official-format malformations ----
    ok_off = bytearray(official_norun([(0, [1, 2, 3])]))
    cases["bad_official_truncated.bin"] = bytes(ok_off[: len(ok_off) - 4])
    swapped = official_norun([(4, [1, 2]), (1, [3, 4])])
    cases["bad_official_nonincreasing.bin"] = swapped
    # run-cookie with absurd container count in the high bits
    cases["bad_official_runcookie_trunc.bin"] = struct.pack("<I", (0xFFFF << 16) | 12347)

    for name, data in sorted(cases.items()):
        with open(os.path.join(OUT, name), "wb") as fh:
            fh.write(data)
    print(f"wrote {len(cases)} corpus files to {OUT}")


if __name__ == "__main__":
    main()
