"""Crash-kill worker: a real OS process for the durability kill matrix.

Launched by tests/test_crashkill.py (NOT collected by pytest). The
worker arms a FaultInjector "kill" rule at one exact durable-write-path
point — inside a group-commit round (pre-fsync / post-fsync-pre-ack),
during a replica ship, at the merge-barrier install, or between a
fragment snapshot and its WAL truncation — then drives the real staged
import path until the injector SIGKILLs the process mid-write. After
each import call RETURNS (i.e. is acked to the caller), the batch index
is appended to the ack log and fsynced, so the parent can counter-assert
"no acked write is ever lost" against exactly what the killed process
had acknowledged.

Batches are derived from their index (seeded RNG), so the parent
regenerates the expected positions without any channel besides the ack
log surviving the kill.
"""

import argparse
import os
import sys
import time

# python <path>/crash_worker.py puts tests/ on sys.path, not the repo
# root the pilosa_tpu package lives in
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def batch_bits(i: int, n_shards: int, n: int = 400):
    """Deterministic batch `i`: (rows, cols) uint64 arrays. The parent
    test regenerates these to verify the replayed state."""
    import numpy as np

    from pilosa_tpu.shardwidth import SHARD_WIDTH

    rng = np.random.default_rng(10_000 + i)
    rows = rng.integers(0, 8, n).astype(np.uint64)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, n).astype(np.uint64)
    return rows, cols


def _ack(fh, i: int) -> None:
    # the ack log is the ground truth the parent audits: flushed AND
    # fsynced per entry, so it is strictly no newer than what the worker
    # actually acknowledged
    fh.write(f"{i}\n")
    fh.flush()
    os.fsync(fh.fileno())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--point", required=True)
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--ack-log", required=True)
    ap.add_argument("--sync-interval", type=float, default=0.0)
    ap.add_argument("--batches", type=int, default=30)
    ap.add_argument("--kill-after", type=int, default=2)
    ap.add_argument("--n-shards", type=int, default=4)
    ap.add_argument("--max-op-n", type=int, default=0)  # 0 = leave default
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from pilosa_tpu.core import wal as walmod
    from pilosa_tpu.server import faults

    walmod.GROUP_COMMIT.configure(sync_interval=args.sync_interval)
    inj = faults.FaultInjector(seed=0)
    point = args.point
    if point == "replica.ship":
        # die while a pool thread is shipping a replica frame
        inj.add_rule("kill", path="/internal/index", skip=args.kill_after)
    else:
        wal_point = (
            "wal." + point if point.startswith("commit.") else point
        )
        inj.add_wal_rule("kill", point=wal_point, skip=args.kill_after)
    faults.install_injector(inj)

    ack = open(args.ack_log, "a")

    if point == "replica.ship":
        from pilosa_tpu.cluster.topology import Node
        from pilosa_tpu.server.node import NodeServer

        a = NodeServer(os.path.join(args.data_dir, "a"), "ck-a")
        b = NodeServer(os.path.join(args.data_dir, "b"), "ck-b")
        a.start()
        b.start()
        members = [
            Node(id=a.node.id, uri=a.node.uri, is_coordinator=True),
            Node(id=b.node.id, uri=b.node.uri),
        ]
        a.set_topology(members, replica_n=2)
        b.set_topology(members, replica_n=2)
        api = a.api
        api.create_index("ck")
        api.create_field("ck", "f", {"type": "set"})
        for i in range(args.batches):
            rows, cols = batch_bits(i, args.n_shards)
            api.import_bits("ck", "f", rows, cols)
            _ack(ack, i)
        print("COMPLETED", flush=True)
        a.stop()
        b.stop()
        return 0

    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder

    h = Holder(args.data_dir).open()
    idx = h.create_index_if_not_exists("ck")
    f = idx.create_field_if_not_exists("f", FieldOptions())
    for i in range(args.batches):
        rows, cols = batch_bits(i, args.n_shards)
        f.import_bits(rows, cols)
        if point == "merge.install" and i % 2 == 1:
            # trigger the cross-fragment merge barrier (the read-side
            # install the kill rule targets)
            f.view("standard").sync_pending()
        if args.max_op_n:
            # lower the snapshot trigger on every fragment the import
            # just created, so the op-count snapshot (and its
            # pre-truncate kill point) fires within a few batches
            for fr in f.view("standard").fragments.values():
                fr.max_op_n = args.max_op_n
        _ack(ack, i)
        if args.sync_interval > 0:
            # bounded-loss mode: pace the batches so background syncer
            # rounds (and the kill point riding them) fire MID-RUN —
            # un-paced, all batches land before the first cadence tick
            time.sleep(args.sync_interval / 5)
    print("COMPLETED", flush=True)
    h.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
