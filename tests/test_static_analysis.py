"""Tier-1 wiring for the static-analysis gate (pilosa_tpu/analysis/).

Two halves:

1. The REAL gate over the repo: every pass, against the committed
   baseline — the same check `python tools/check.py` runs. A new raw
   lock, a sleep under a mutex, an impure jit body, an undeclared stat
   name, or an undocumented config knob fails tier-1 right here with
   file:line evidence.
2. The gate's own behavior on seeded violations: each pass family must
   fire (with correct location) on a synthetic bad module, stale
   baseline entries must fail, and unjustified baseline entries must be
   rejected at load time.
"""

import ast
import os
import subprocess
import sys
import textwrap

import pytest

from pilosa_tpu import analysis
from pilosa_tpu.analysis.framework import (
    Baseline,
    BaselineEntry,
    Module,
    run_gate,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "analysis_baseline.toml")


def seeded_module(rel: str, src: str) -> Module:
    src = textwrap.dedent(src)
    return Module(
        path=os.path.join("/tmp", rel),
        rel=rel,
        source=src,
        tree=ast.parse(src),
    )


def findings_for(src: str, rel: str = "pilosa_tpu/_seeded.py"):
    return analysis.run_passes(
        analysis.default_passes(), [seeded_module(rel, src)]
    )


# ---------------------------------------------------------------------------
# the real gate
# ---------------------------------------------------------------------------


class TestRepoGate:
    def test_repo_is_clean_under_committed_baseline(self):
        result = analysis.check(REPO, baseline_path=BASELINE)
        assert result.ok, "\n" + result.render()

    def test_baseline_is_small_and_fully_justified(self):
        b = Baseline.load(BASELINE)
        assert b.entries, "baseline exists but is empty?"
        for e in b.entries:
            assert len(e.reason.strip()) > 40, (
                f"baseline entry {e.code}/{e.path} has a perfunctory "
                "reason — document WHY the violation is intentional"
            )

    def test_check_script_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check.py")],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "check: OK" in proc.stdout


# ---------------------------------------------------------------------------
# lock hygiene on seeded violations
# ---------------------------------------------------------------------------


class TestLockHygieneSeeded:
    def test_raw_lock_outside_locks_py(self):
        fs = findings_for(
            """
            import threading
            _MU = threading.Lock()
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK001"]
        assert f.line == 3
        assert "TrackedLock" in f.message

    def test_raw_lock_inside_locks_py_allowed(self):
        fs = analysis.run_passes(
            analysis.default_passes(),
            [
                seeded_module(
                    "pilosa_tpu/utils/locks.py",
                    "import threading\n_MU = threading.Lock()\n",
                )
            ],
        )
        assert not [f for f in fs if f.code == "LOCK001"]

    def test_sleep_under_lock(self):
        fs = findings_for(
            """
            import time
            from pilosa_tpu.utils.locks import TrackedLock
            _MU = TrackedLock("x")

            def f():
                with _MU:
                    time.sleep(1.0)
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK002"]
        assert f.line == 8
        assert "time.sleep" in f.message and "_MU" in f.message

    def test_network_io_under_self_lock(self):
        fs = findings_for(
            """
            import urllib.request

            class C:
                def f(self):
                    with self._mu:
                        urllib.request.urlopen("http://x")
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK002"]
        assert "urlopen" in f.message

    def test_device_sync_under_lock(self):
        fs = findings_for(
            """
            class C:
                def f(self):
                    with self._lock:
                        return self.arr.block_until_ready()
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK003"]
        assert "block_until_ready" in f.message

    def test_closure_under_lock_not_flagged(self):
        # a function DEFINED under the lock runs later: not a hold
        fs = findings_for(
            """
            import time

            class C:
                def f(self):
                    with self._mu:
                        def later():
                            time.sleep(1.0)
                        self.cb = later
            """
        )
        assert not [f for f in fs if f.code == "LOCK002"]


# ---------------------------------------------------------------------------
# guarded-by inference (LOCK004/LOCK005) on seeded violations
# ---------------------------------------------------------------------------


class TestGuardedBySeeded:
    def test_mixed_write_fires_lock004(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._items = {}

                def add(self, k, v):
                    with self._mu:
                        self._items[k] = v

                def replace(self, items):
                    with self._mu:
                        self._items = dict(items)

                def rogue(self):
                    self._items = {}   # line 18: bare write
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK004"]
        assert f.line == 18
        assert "C._items" in f.message and "'_mu'" in f.message

    def test_init_writes_are_exempt(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._a = {}
                    self._a["k"] = 1   # constructor: pre-publication

                def w1(self, v):
                    with self._mu:
                        self._a["x"] = v

                def w2(self, v):
                    with self._mu:
                        self._a["y"] = v
            """
        )
        assert not [f for f in fs if f.code in ("LOCK004", "LOCK005")]

    def test_single_write_site_claims_no_guard(self):
        # MIN_GUARDED_WRITES: one agreeing site is too little signal
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")

                def a(self, v):
                    with self._mu:
                        self._x = v

                def b(self, v):
                    self._x = v
            """
        )
        assert not [f for f in fs if f.code == "LOCK004"]

    def test_bare_read_in_lock_taking_method_fires_lock005(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._n = 0

                def bump(self):
                    with self._mu:
                        self._n += 1

                def bump2(self):
                    with self._mu:
                        self._n += 1

                def peek_then_lock(self):
                    n = self._n        # line 18: bare read...
                    with self._mu:     # ...in a method that takes _mu
                        self._n += 1
                    return n
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK005"]
        assert f.line == 18
        assert "peek_then_lock" in f.message

    def test_bare_read_in_lockless_method_not_flagged(self):
        # LOCK005 scopes to methods that ELSEWHERE take the lock: a
        # gauge-snapshot method that never does is inference-silent
        # (the runtime race detector owns that territory)
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._n = 0

                def bump(self):
                    with self._mu:
                        self._n += 1

                def bump2(self):
                    with self._mu:
                        self._n += 1

                def snapshot(self):
                    return self._n
            """
        )
        assert not [f for f in fs if f.code == "LOCK005"]

    def test_guarded_by_annotation_enforces_single_write(self):
        # a declared guard fires on ANY bare write, even below the
        # inference threshold
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._x = 0   # guarded-by: _mu

                def locked_write(self, v):
                    with self._mu:
                        self._x = v

                def rogue(self, v):
                    self._x = v
            """
        )
        (f,) = [f for f in fs if f.code == "LOCK004"]
        assert "guard declared by annotation" in f.message

    def test_lock_free_annotation_exempts_attribute(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._x = 0   # lock-free: monotonic int, GIL-atomic reads

                def a(self, v):
                    with self._mu:
                        self._x = v

                def b(self, v):
                    with self._mu:
                        self._x = v

                def rogue(self, v):
                    self._x = v
            """
        )
        assert not [f for f in fs if f.code in ("LOCK004", "LOCK005")]

    def test_lock_free_annotation_without_reason_is_a_finding(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._x = 0   # lock-free:

                def a(self, v):
                    with self._mu:
                        self._x = v
            """
        )
        assert any(
            f.code == "LOCK004" and "no reason" in f.message for f in fs
        )

    def test_locked_suffix_methods_assume_primary_lock(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._x = 0

                def a(self, v):
                    with self._mu:
                        self._set_locked(v)

                def b(self, v):
                    with self._mu:
                        self._set_locked(v)

                def _set_locked(self, v):
                    self._x = v   # convention: caller holds _mu
            """
        )
        assert not [f for f in fs if f.code == "LOCK004"]

    def test_def_level_guarded_by_annotation(self):
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._x = 0

                def a(self, v):
                    with self._mu:
                        self._apply(v)

                def b(self, v):
                    with self._mu:
                        self._apply(v)

                def _apply(self, v):  # guarded-by: _mu (callers hold it)
                    self._x = v
            """
        )
        assert not [f for f in fs if f.code == "LOCK004"]

    def test_condition_aliases_its_lock(self):
        # `with self._cv:` acquires the underlying _mu — one guard
        fs = findings_for(
            """
            from pilosa_tpu.utils.locks import TrackedCondition, TrackedLock

            class C:
                def __init__(self):
                    self._mu = TrackedLock("c.mu")
                    self._cv = TrackedCondition(self._mu, name="c.cv")
                    self._x = 0

                def a(self, v):
                    with self._cv:
                        self._x = v

                def b(self, v):
                    with self._mu:
                        self._x = v
            """
        )
        assert not [f for f in fs if f.code == "LOCK004"]


# ---------------------------------------------------------------------------
# dispatch discipline (LOCK006) on seeded violations
# ---------------------------------------------------------------------------


class TestDispatchDisciplineSeeded:
    REL = "pilosa_tpu/exec/_seeded.py"

    def test_direct_jit_call_flagged(self):
        fs = findings_for(
            """
            import jax

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                return _tally(x)
            """,
            rel=self.REL,
        )
        (f,) = [f for f in fs if f.code == "LOCK006"]
        assert "_tally" in f.message and "PR-10" in f.message

    def test_block_until_ready_flagged(self):
        fs = findings_for(
            """
            def leg(arr):
                return arr.block_until_ready()
            """,
            rel=self.REL,
        )
        (f,) = [f for f in fs if f.code == "LOCK006"]
        assert "block_until_ready" in f.message

    def test_run_serialized_argument_exempt(self):
        fs = findings_for(
            """
            import jax
            from pilosa_tpu.exec.plan import run_serialized

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                return run_serialized(lambda: _tally(x))
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_run_serialized_eager_argument_still_flagged(self):
        # run_serialized(_tally(x)) evaluates the compiled call EAGERLY
        # on the calling thread before run_serialized runs — the PR-10
        # bug wearing the fix's clothes; only deferred callables
        # (lambda / function reference) are exempt
        fs = findings_for(
            """
            import jax
            from pilosa_tpu.exec.plan import run_serialized

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                return run_serialized(_tally(x))
            """,
            rel=self.REL,
        )
        (f,) = [f for f in fs if f.code == "LOCK006"]
        assert "_tally" in f.message

    def test_run_serialized_function_reference_exempt(self):
        fs = findings_for(
            """
            import jax
            from pilosa_tpu.exec.plan import run_serialized

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                return run_serialized(_tally)
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_dispatch_mutex_with_block_exempt(self):
        fs = findings_for(
            """
            import jax
            from pilosa_tpu.exec.plan import dispatch_mutex

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                with dispatch_mutex():
                    return _tally(x).block_until_ready()
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_jit_body_calls_are_traced_not_dispatched(self):
        fs = findings_for(
            """
            import jax

            @jax.jit
            def _inner(x):
                return x

            @jax.jit
            def _outer(x):
                return _inner(x)   # inlined into one program
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_out_of_scope_modules_not_checked(self):
        fs = findings_for(
            """
            import jax

            @jax.jit
            def _tally(x):
                return x

            def leg(x):
                return _tally(x)
            """,
            rel="pilosa_tpu/server/_seeded.py",
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_dispatch_ok_annotation_exempts_with_reason(self):
        fs = findings_for(
            """
            import jax

            @jax.jit
            def _tally(x):
                return x

            def leg(x):  # dispatch-ok: single-device, no collectives
                return _tally(x)
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK006"]

    def test_dispatch_ok_without_reason_is_a_finding(self):
        fs = findings_for(
            """
            import jax

            @jax.jit
            def _tally(x):
                return x

            def leg(x):  # dispatch-ok:
                return _tally(x)
            """,
            rel=self.REL,
        )
        assert any(
            f.code == "LOCK006" and "no reason" in f.message for f in fs
        )


# ---------------------------------------------------------------------------
# fragment-lock durability discipline (LOCK007) on seeded violations
# ---------------------------------------------------------------------------


class TestFragmentLockDurabilitySeeded:
    REL = "pilosa_tpu/core/_seeded.py"

    def test_os_fsync_under_fragment_lock(self):
        fs = findings_for(
            """
            import os

            class F:
                def write(self, fd):
                    with self._mu:
                        os.fsync(fd)
            """,
            rel=self.REL,
        )
        (f,) = [f for f in fs if f.code == "LOCK007"]
        assert "os.fsync" in f.message and "PR-11" in f.message

    def test_wait_durable_under_fragment_lock(self):
        fs = findings_for(
            """
            from pilosa_tpu.core import wal as walmod

            class F:
                def write(self, tok):
                    with self._mu:
                        walmod.GROUP_COMMIT.wait_durable(tok)
            """,
            rel=self.REL,
        )
        (f,) = [f for f in fs if f.code == "LOCK007"]
        assert "wait_durable" in f.message

    def test_wal_truncate_under_fragment_lock(self):
        fs = findings_for(
            """
            class F:
                def snap(self):
                    with self._mu:
                        self._wal.truncate()
            """,
            rel=self.REL,
        )
        assert [f for f in fs if f.code == "LOCK007"]

    def test_commit_token_past_the_lock_passes(self):
        # the PR-11 convention itself: token returned past the lock
        fs = findings_for(
            """
            from pilosa_tpu.core import wal as walmod

            class F:
                def write(self, positions):
                    with self._mu:
                        tok = self._wal.append(0, positions)
                    if tok is not None:
                        walmod.GROUP_COMMIT.wait_durable(tok)
            """,
            rel=self.REL,
        )
        assert not [f for f in fs if f.code == "LOCK007"]

    def test_out_of_scope_modules_not_checked(self):
        fs = findings_for(
            """
            import os

            class F:
                def write(self, fd):
                    with self._mu:
                        os.fsync(fd)
            """,
            rel="pilosa_tpu/server/_seeded2.py",
        )
        assert not [f for f in fs if f.code == "LOCK007"]


# ---------------------------------------------------------------------------
# jax purity on seeded violations
# ---------------------------------------------------------------------------


class TestJaxPuritySeeded:
    def test_impure_jit_body_all_rules(self):
        fs = findings_for(
            """
            import functools
            import jax
            import numpy as np

            STATS = {"n": 0}

            @functools.partial(jax.jit, static_argnames=("k",))
            def g(x, k):
                print("traced")
                STATS["n"] += 1
                v = np.sum(x)
                return float(x) + x.item() + v
            """
        )
        codes = {f.code for f in fs}
        assert {"JAX001", "JAX002", "JAX003", "JAX004"} <= codes
        np_finding = [f for f in fs if f.code == "JAX002"][0]
        assert "numpy.sum" in np_finding.message
        assert np_finding.line == 12

    def test_static_argnames_mismatch(self):
        fs = findings_for(
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("missing",))
            def g(x):
                return x
            """
        )
        (f,) = [f for f in fs if f.code == "JAX005"]
        assert "'missing'" in f.message and "g()" in f.message

    def test_static_argnums_out_of_range(self):
        fs = findings_for(
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnums=(5,))
            def g(x):
                return x
            """
        )
        (f,) = [f for f in fs if f.code == "JAX005"]
        assert "out of range" in f.message

    def test_static_coercion_allowed(self):
        # int() of a STATIC argument is legal (it is a Python value)
        fs = findings_for(
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def g(x, k):
                return x * int(k)
            """
        )
        assert not [f for f in fs if f.code == "JAX003"]

    def test_pallas_kernel_body_checked(self):
        fs = findings_for(
            """
            import jax
            from jax.experimental import pallas as pl

            def kernel(x_ref, o_ref):
                print("impure")
                o_ref[...] = x_ref[...]

            def call(x):
                return pl.pallas_call(kernel, out_shape=None)(x)
            """
        )
        (f,) = [f for f in fs if f.code == "JAX001"]
        assert "kernel()" in f.message

    def test_pure_jit_clean(self):
        fs = findings_for(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def g(x):
                return jnp.sum(x)
            """
        )
        assert not [f for f in fs if f.code.startswith("JAX")]


# ---------------------------------------------------------------------------
# api invariants on seeded violations
# ---------------------------------------------------------------------------


class TestApiInvariantsSeeded:
    def _with_repo_registry(self, src: str):
        """Seeded module + the real stats.py (for its STAT_NAMES)."""
        stats_path = os.path.join(REPO, "pilosa_tpu", "utils", "stats.py")
        stats_mod = analysis.load_source_module(
            stats_path, rel="pilosa_tpu/utils/stats.py"
        )
        return analysis.run_passes(
            [analysis.ApiInvariantsPass()],
            [stats_mod, seeded_module("pilosa_tpu/_seeded.py", src)],
        )

    def test_undeclared_stat_emission(self):
        fs = self._with_repo_registry(
            """
            class C:
                def f(self):
                    self.stats.count("definitely_not_declared")
            """
        )
        assert any(
            f.code == "API001" and "definitely_not_declared" in f.message
            for f in fs
        )

    def test_dynamic_stat_outside_declared_prefix(self):
        fs = self._with_repo_registry(
            """
            class C:
                def f(self, x):
                    self.stats.count(f"mystery.{x}")
            """
        )
        assert any(
            f.code == "API001" and "mystery." in f.message for f in fs
        )

    def test_with_tags_chain_emission_scanned(self):
        """The inline labeled-family form
        `stats.with_tags(...).gauge(...)` is a real emission: an
        undeclared name through the chain must be flagged (and a
        declared one keeps its registry entry non-stale)."""
        fs = self._with_repo_registry(
            """
            class C:
                def f(self):
                    self.stats.with_tags("index:a").count("chain_undeclared")
            """
        )
        assert any(
            f.code == "API001" and "chain_undeclared" in f.message
            for f in fs
        )

    def test_api008_stat_labels_must_name_declared_stats(self):
        stats_mod = seeded_module(
            "pilosa_tpu/utils/stats.py",
            """
            STAT_NAMES = frozenset({"real.metric"})
            STAT_PREFIXES = frozenset({"dyn."})
            STAT_LABELS = {
                "real.metric": ("index",),   # fine
                "dyn.family": ("node",),     # fine via prefix
                "typo.metric": ("index",),   # API008: undeclared
                "real.metric2": (),          # ...and undeclared + empty
            }
            """,
        )
        emitter = seeded_module(
            "pilosa_tpu/_seeded.py",
            """
            class C:
                def f(self):
                    self.stats.count("real.metric")
            """,
        )
        fs = analysis.run_passes(
            [analysis.ApiInvariantsPass()], [stats_mod, emitter]
        )
        assert any(
            f.code == "API008" and "typo.metric" in f.message for f in fs
        )
        assert any(
            f.code == "API008"
            and "real.metric2" in f.message
            and "no label keys" in f.message
            for f in fs
        )
        assert not any(
            f.code == "API008" and "dyn.family" in f.message for f in fs
        )

    def test_declared_prefix_dynamic_ok(self):
        fs = self._with_repo_registry(
            """
            class C:
                def f(self, state):
                    self.stats.count(f"breaker.{state}")
            """
        )
        assert not [
            f
            for f in fs
            if f.code == "API001" and "breaker." in f.message
        ]

    def _with_span_registry(self, src: str):
        """Seeded module + the real tracing.py (for its SPAN_NAMES)."""
        tracing_path = os.path.join(
            REPO, "pilosa_tpu", "utils", "tracing.py"
        )
        tracing_mod = analysis.load_source_module(
            tracing_path, rel="pilosa_tpu/utils/tracing.py"
        )
        return analysis.run_passes(
            [analysis.ApiInvariantsPass()],
            [tracing_mod, seeded_module("pilosa_tpu/_seeded.py", src)],
        )

    def test_undeclared_span_start(self):
        fs = self._with_span_registry(
            """
            class C:
                def f(self):
                    with self.tracer.start_span("mystery.stage"):
                        pass
            """
        )
        assert any(
            f.code == "API006" and "mystery.stage" in f.message for f in fs
        )

    def test_undeclared_synthetic_span(self):
        fs = self._with_span_registry(
            """
            from pilosa_tpu.utils import tracing

            def f():
                tracing.record_span("rogue.synthetic", 0.1)
            """
        )
        assert any(
            f.code == "API006" and "rogue.synthetic" in f.message
            for f in fs
        )

    def test_declared_span_ok_and_stale_entry_flagged(self):
        fs = self._with_span_registry(
            """
            class C:
                def f(self):
                    with self.tracer.start_span("api.query"):
                        pass
            """
        )
        assert not [
            f
            for f in fs
            if f.code == "API006" and "api.query" in f.message
        ]
        # nothing in the seeded set starts exec.dispatch -> stale entry
        assert any(
            f.code == "API007" and "exec.dispatch" in f.message for f in fs
        )

    def test_config_flag_doc_invariants(self, tmp_path):
        config_src = textwrap.dedent(
            """
            from dataclasses import dataclass

            @dataclass
            class ClusterConfig:
                replicas: int = 1
                secret_knob: float = 0.0

            @dataclass
            class Config:
                bind: str = "localhost:1"
                cluster: ClusterConfig = None
            """
        )
        main_src = textwrap.dedent(
            """
            import argparse

            def build():
                p = argparse.ArgumentParser()
                sub = p.add_subparsers()
                sp = sub.add_parser("server")
                sp.add_argument("--bind")
                sp.add_argument("--replicas")
                sp.add_argument("--orphan-flag")
                return p
            """
        )
        docs = tmp_path / "configuration.md"
        docs.write_text("bind = ...\nreplicas = ...\n")  # secret-knob absent
        config_mod = Module(
            path=str(tmp_path / "config.py"),
            rel="pilosa_tpu/cli/config.py",
            source=config_src,
            tree=ast.parse(config_src),
        )
        main_mod = Module(
            path=str(tmp_path / "main.py"),
            rel="pilosa_tpu/cli/main.py",
            source=main_src,
            tree=ast.parse(main_src),
        )
        fs = analysis.run_passes(
            [analysis.ApiInvariantsPass(docs_path=str(docs))],
            [config_mod, main_mod],
        )
        codes = {(f.code, f.message) for f in fs}
        assert any(
            c == "API003" and "secret_knob" in m for c, m in codes
        ), fs  # undocumented knob
        assert any(
            c == "API004" and "orphan-flag" in m for c, m in codes
        ), fs  # flag with no knob
        assert any(
            c == "API005" and "secret_knob" in m for c, m in codes
        ), fs  # knob with no flag

    def test_non_stats_receivers_ignored(self):
        fs = self._with_repo_registry(
            """
            class C:
                def f(self, rb):
                    return rb.count() + self.plan.count()
            """
        )
        assert not [f for f in fs if f.code == "API001"]


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


class TestBaseline:
    def test_stale_entry_fails_gate(self):
        baseline = Baseline(
            entries=[
                BaselineEntry(
                    code="LOCK002",
                    path="pilosa_tpu/nowhere.py",
                    match="",
                    reason="entry that matches nothing",
                    rule="lock-hygiene",
                )
            ]
        )
        result = run_gate(analysis.default_passes(), [], baseline)
        assert not result.ok
        assert result.stale_entries and "STALE" in result.render()

    def test_baseline_suppresses_matching_finding(self):
        m = seeded_module(
            "pilosa_tpu/_seeded.py",
            """
            import time
            from pilosa_tpu.utils.locks import TrackedLock
            _MU = TrackedLock("x")

            def f():
                with _MU:
                    time.sleep(1.0)
            """,
        )
        baseline = Baseline(
            entries=[
                BaselineEntry(
                    code="LOCK002",
                    path="pilosa_tpu/_seeded.py",
                    match="time.sleep",
                    reason="seeded on purpose for this test",
                    rule="lock-hygiene",
                )
            ]
        )
        result = run_gate([analysis.LockHygienePass()], [m], baseline)
        assert result.ok, result.render()
        assert len(result.suppressed) == 1

    def test_unjustified_entry_rejected_at_load(self, tmp_path):
        p = tmp_path / "baseline.toml"
        p.write_text(
            '[[allow]]\ncode = "LOCK002"\npath = "x.py"\nmatch = ""\n'
            'rule = "lock-hygiene"\n'
        )
        with pytest.raises(ValueError, match="reason"):
            Baseline.load(str(p))

    def test_entry_without_rule_rejected_at_load(self, tmp_path):
        p = tmp_path / "baseline.toml"
        p.write_text(
            '[[allow]]\ncode = "LOCK002"\npath = "x.py"\nmatch = ""\n'
            'reason = "justified but unowned"\n'
        )
        with pytest.raises(ValueError, match="rule"):
            Baseline.load(str(p))

    def test_entry_naming_removed_pass_fails_gate(self):
        # a renamed/retired pass must take its suppressions with it
        baseline = Baseline(
            entries=[
                BaselineEntry(
                    code="LOCK002",
                    path="pilosa_tpu/x.py",
                    match="",
                    reason="suppression owned by a pass that is gone",
                    rule="lock-hygiene-v1",
                )
            ]
        )
        result = run_gate(analysis.default_passes(), [], baseline)
        assert not result.ok
        assert result.invalid_entries
        assert "lock-hygiene-v1" in result.render()

    def test_entry_naming_removed_rule_code_fails_gate(self):
        baseline = Baseline(
            entries=[
                BaselineEntry(
                    code="LOCK099",
                    path="pilosa_tpu/x.py",
                    match="",
                    reason="suppression for a rule code that is gone",
                    rule="lock-hygiene",
                )
            ]
        )
        result = run_gate(analysis.default_passes(), [], baseline)
        assert not result.ok
        assert result.invalid_entries
        assert "LOCK099" in result.render()

    def test_every_pass_declares_its_rules(self):
        # the validation above is only as good as the declarations: a
        # pass emitting codes it never declared would let its baseline
        # entries be rejected as invalid (or worse, never validated)
        for p in analysis.default_passes():
            assert p.rules, f"pass {p.name} declares no rules"
            for code in p.rules:
                assert code[:3] in ("LOC", "JAX", "API", "RES"), code

    def test_committed_baseline_entries_all_name_live_rules(self):
        from pilosa_tpu.analysis.framework import validate_baseline

        b = Baseline.load(BASELINE)
        assert validate_baseline(analysis.default_passes(), b) == []

    def test_gate_failure_carries_file_line_evidence(self):
        m = seeded_module(
            "pilosa_tpu/_seeded.py",
            """
            import threading
            _MU = threading.Lock()
            """,
        )
        result = run_gate(analysis.default_passes(), [m], Baseline())
        assert not result.ok
        assert "pilosa_tpu/_seeded.py:3" in result.render()


# ---------------------------------------------------------------------------
# resource lifecycle (RES001-RES005) on seeded violations
# ---------------------------------------------------------------------------


class TestLifecycleSeeded:
    """The must-release pass against synthetic modules, one rule at a
    time. CFG shape coverage (finally clones, with-unwind, loop exits)
    lives in test_resource_lifecycle.py; these pin the rule semantics."""

    def _lifecycle(self, src: str, rel: str = "pilosa_tpu/_seeded.py"):
        """Seeded module + the real ledger module (so RES005's
        cross-check sees the registry and stays quiet)."""
        res_mod = analysis.load_source_module(
            os.path.join(REPO, "pilosa_tpu", "utils", "resources.py"),
            rel="pilosa_tpu/utils/resources.py",
        )
        return analysis.run_passes(
            [analysis.LifecyclePass()], [res_mod, seeded_module(rel, src)]
        )

    def test_res001_branch_arm_skips_release(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f(flag):
                pool = ThreadPoolExecutor(max_workers=2)
                if flag:
                    pool.shutdown()
            """
        )
        assert any(f.code == "RES001" and f.line == 5 for f in fs), fs

    def test_release_on_every_path_is_clean(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f(work):
                pool = ThreadPoolExecutor(max_workers=2)
                try:
                    work(pool)
                finally:
                    pool.shutdown()
            """
        )
        assert not [f for f in fs if f.code.startswith("RES")], fs

    def test_res002_exception_path_skips_release(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f(work):
                pool = ThreadPoolExecutor(max_workers=2)
                work(pool)
                pool.shutdown()
            """
        )
        codes = {f.code for f in fs}
        assert "RES002" in codes, fs
        assert "RES001" not in codes, fs  # the straight-line path is fine

    def test_res003_discarded_handle(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f():
                ThreadPoolExecutor(max_workers=2)
            """
        )
        assert any(f.code == "RES003" and f.line == 5 for f in fs), fs

    def test_daemon_thread_exempt_nondaemon_tracked(self):
        fs = self._lifecycle(
            """
            import threading

            def f(cb):
                t = threading.Thread(target=cb, daemon=True)
                t.start()

            def g(cb):
                t = threading.Thread(target=cb)
                t.start()
            """
        )
        assert not [f for f in fs if f.line == 5], fs  # daemon: exempt
        assert any(f.code == "RES001" and f.line == 9 for f in fs), fs

    def test_res004_empty_reason_and_stale_annotation(self):
        fs = self._lifecycle(
            """
            def f():
                x = 1  # owns:
                y = 2  # transfer: consumed by nothing in this module
            """
        )
        assert [f.code for f in fs].count("RES004") == 2, fs

    def test_res005_registry_drift_both_ways(self):
        fake = seeded_module(
            "pilosa_tpu/utils/resources.py",
            """
            RESOURCE_CLASSES = {
                "sched.ticket": "kept",
                "made.up": "ledger entry with no contract",
            }
            """,
        )
        fs = analysis.run_passes([analysis.LifecyclePass()], [fake])
        msgs = [f.message for f in fs if f.code == "RES005"]
        assert any("made.up" in m for m in msgs), fs
        assert any("hbm.pin" in m for m in msgs), fs

    def test_res005_missing_ledger_module(self):
        fs = analysis.run_passes(
            [analysis.LifecyclePass()],
            [seeded_module("pilosa_tpu/_seeded.py", "x = 1\n")],
        )
        assert any(
            f.code == "RES005" and "missing" in f.message for f in fs
        ), fs

    def test_owns_annotation_suppresses_with_reason(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f(registry):
                # owns: registry shuts every pool down at process exit
                pool = ThreadPoolExecutor(max_workers=2)
                registry.append(pool)
            """
        )
        assert not [f for f in fs if f.code.startswith("RES")], fs

    def test_conditional_acquire_with_identity_guard_is_clean(self):
        # `x = acquire() if c else None` + `if x is not None: x.release()`
        # — branch pruning plus the no-exception-edge identity test
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f(flag):
                pool = ThreadPoolExecutor(max_workers=2) if flag else None
                if pool is not None:
                    pool.shutdown()
            """
        )
        assert not [f for f in fs if f.code.startswith("RES")], fs

    def test_with_and_return_are_transfer_by_construction(self):
        fs = self._lifecycle(
            """
            from concurrent.futures import ThreadPoolExecutor

            def f():
                with ThreadPoolExecutor(max_workers=2) as pool:
                    pool.submit(print)

            def g():
                return ThreadPoolExecutor(max_workers=2)

            def h():
                pool = ThreadPoolExecutor(max_workers=2)
                return pool
            """
        )
        assert not [f for f in fs if f.code.startswith("RES")], fs

    def test_manual_lock_acquire_must_release(self):
        fs = self._lifecycle(
            """
            class C:
                def bad(self, work):
                    self._mu.acquire()
                    work()

                def good(self, work):
                    self._mu.acquire()
                    try:
                        work()
                    finally:
                        self._mu.release()
            """
        )
        bad = [f for f in fs if f.line == 4]
        assert any(f.code == "RES002" for f in bad), fs
        assert not [f for f in fs if f.line == 8], fs

    def test_site_mode_pin_requires_kwarg_match(self):
        fs = self._lifecycle(
            """
            def f(cache, key, build):
                arr = cache.get_or_build(key, build, pin=True)
                return arr

            def g(cache, key, build):
                arr = cache.get_or_build(key, build)
                return arr
            """
        )
        assert any(f.code == "RES001" and f.line == 3 for f in fs), fs
        assert not [f for f in fs if f.line == 7], fs


class TestApi009Seeded:
    def test_unread_knob_flagged_read_knob_quiet(self):
        cfg = seeded_module(
            "pilosa_tpu/cli/config.py",
            """
            from dataclasses import dataclass

            @dataclass
            class Config:
                used_knob: int = 1
                dead_knob: int = 2
            """,
        )
        reader = seeded_module(
            "pilosa_tpu/server/consumer.py",
            """
            def f(cfg):
                return cfg.used_knob
            """,
        )
        fs = analysis.run_passes(
            [analysis.ApiInvariantsPass()], [cfg, reader]
        )
        api9 = [f for f in fs if f.code == "API009"]
        assert len(api9) == 1, fs
        assert "dead_knob" in api9[0].message
        assert api9[0].line == 7

    def test_knob_read_only_in_config_module_is_still_dead(self):
        cfg = seeded_module(
            "pilosa_tpu/cli/config.py",
            """
            from dataclasses import dataclass

            @dataclass
            class Config:
                self_knob: int = 1

                def validate(self):
                    return self.self_knob > 0
            """,
        )
        fs = analysis.run_passes([analysis.ApiInvariantsPass()], [cfg])
        assert any(
            f.code == "API009" and "self_knob" in f.message for f in fs
        ), fs
