"""Round-3 cluster behaviors: parallel fan-out, holder cleaner, status
acknowledgement, import durability reporting, wire/BSI bounds.

Reference parity targets: executor.go:2522 (mapper goroutine per node),
holder.go:1126 (holderCleaner.CleanHolder), cluster.go resize status
broadcasts, api.go Import fan-out.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def http_json(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def wait_job(uri, want="DONE", timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = http_json("GET", f"{uri}/cluster/resize/job")
        if job["state"] != "RUNNING":
            assert job["state"] == want, job
            return job
        time.sleep(0.05)
    raise AssertionError("resize job did not finish")


# ---------------------------------------------------------------------------
# parallel fan-out
# ---------------------------------------------------------------------------


def test_slow_peer_does_not_serialize_fanout():
    """One slow node must not add its latency to every other node's
    request: with 3 remote peers each stubbed to 0.4 s, a fan-out query
    finishes in ~1x the delay, not 3x (executor.go:2522)."""
    with ClusterHarness(4, in_memory=True) as c:
        api = c[0].api
        api.create_index("p")
        api.create_field("p", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 1 for s in range(16)]
        api.import_bits("p", "f", [0] * len(cols), cols)
        (expect,) = api.query("p", "Count(Row(f=0))")
        assert expect == len(cols)

        real = c[0].client.query_node
        delay = 0.4

        def slow(uri, *a, **kw):
            time.sleep(delay)
            return real(uri, *a, **kw)

        c[0].client.query_node = slow
        try:
            t0 = time.perf_counter()
            (got,) = c[0].api.query("p", "Count(Row(f=0))")
            dt = time.perf_counter() - t0
        finally:
            c[0].client.query_node = real
        assert got == expect
        # 3 peers x 0.4 s serial would be >= 1.2 s; parallel ~0.4 s
        assert dt < 2.5 * delay, f"fan-out took {dt:.2f}s — serialized?"


def test_slow_peer_does_not_serialize_write_broadcast():
    """Shard announcements/broadcasts go to peers concurrently."""
    with ClusterHarness(4, in_memory=True) as c:
        api = c[0].api
        api.create_index("pb")
        api.create_field("pb", "f", {"type": "set"})
        real = c[0].client.send_message
        delay = 0.3

        def slow(uri, msg, **kw):
            time.sleep(delay)
            return real(uri, msg)

        c[0].client.send_message = slow
        try:
            t0 = time.perf_counter()
            api.query("pb", f"Set({3 * SHARD_WIDTH}, f=1)")
            dt = time.perf_counter() - t0
        finally:
            c[0].client.send_message = real
        assert dt < 3 * delay, f"announce took {dt:.2f}s — serialized?"


# ---------------------------------------------------------------------------
# holder cleaner (holder.go:1126)
# ---------------------------------------------------------------------------


def _local_shards(srv, index):
    out = set()
    idx = srv.holder.index(index)
    for f in idx.fields(include_hidden=True):
        for v in f.views.values():
            out |= set(v.fragments)
    return out


def test_holder_cleaner_after_join():
    """After a node joins, previous owners drop the fragments the new
    topology reassigned away from them — no disk/devcache leak."""
    with ClusterHarness(2, in_memory=True) as c:
        api = c[0].api
        api.create_index("hc")
        api.create_field("hc", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 7 for s in range(24)]
        api.import_bits("hc", "f", [0] * len(cols), cols)
        joiner = NodeServer(None, "cleaner-joiner").start()
        try:
            uri = c[0].node.uri
            http_json(
                "POST", f"{uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            wait_job(uri)
            # joiner owns some shards now
            gained = _local_shards(joiner, "hc")
            assert gained
            # every node retains ONLY fragments for shards it owns
            for s in [c[0], c[1], joiner]:
                for shard in _local_shards(s, "hc"):
                    owners = {n.id for n in s.cluster.shard_nodes("hc", shard)}
                    assert s.node.id in owners, (s.node.id, shard)
            # data still complete
            for s in [c[0], c[1], joiner]:
                (cnt,) = s.api.query("hc", "Count(Row(f=0))")
                assert cnt == len(cols), s.node.id
        finally:
            joiner.stop()


def test_holder_cleaner_after_remove():
    """After remove-node, survivors that lost ownership drop those
    fragments while gainers serve them (VERDICT r2 #5 done-criterion)."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("hr")
        api.create_field("hr", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 3 for s in range(24)]
        api.import_bits("hr", "f", [0] * len(cols), cols)
        uri = c[0].node.uri
        http_json(
            "POST", f"{uri}/cluster/resize/remove-node", {"id": c[2].node.id}
        )
        wait_job(uri)
        for s in [c[0], c[1]]:
            assert len(s.cluster.nodes) == 2
            for shard in _local_shards(s, "hr"):
                owners = {n.id for n in s.cluster.shard_nodes("hr", shard)}
                assert s.node.id in owners, (s.node.id, shard)
            (cnt,) = s.api.query("hr", "Count(Row(f=0))")
            assert cnt == len(cols), s.node.id


# ---------------------------------------------------------------------------
# status acknowledgement (r2 advisor medium)
# ---------------------------------------------------------------------------


def test_missed_restore_aborts_job():
    """A member that cannot acknowledge the final NORMAL restore fails the
    job (rolled back) instead of silently reporting DONE while that member
    stays frozen in RESIZING."""
    with ClusterHarness(2, in_memory=True) as c:
        old_ids = {n.id for n in c[0].cluster.nodes}
        real = c[0].client.send_message
        target = c[1].node.uri

        def flaky(uri, msg, **kw):
            # fail ONLY the restore that announces the grown (3-node)
            # membership; the rollback broadcast (old 2-node membership)
            # must still get through and unfreeze the member
            if (
                uri == target
                and msg.get("type") == "cluster-status"
                and msg.get("state") == "NORMAL"
                and len(msg.get("nodes", [])) == 3
            ):
                from pilosa_tpu.server.client import ClientError

                raise ClientError("injected: restore lost")
            return real(uri, msg)

        joiner = NodeServer(None, "ack-joiner").start()
        c[0].client.send_message = flaky
        try:
            http_json(
                "POST", f"{c[0].node.uri}/cluster/join",
                {"id": joiner.node.id, "uri": joiner.node.uri},
            )
            job = wait_job(c[0].node.uri, want="ABORTED", timeout=60)
            assert "not acknowledged" in job["error"]
        finally:
            c[0].client.send_message = real
            joiner.stop()
        # rollback restored the old membership AND unfroze every member
        # (only the restore-to-new-membership was dropped)
        for s in (c[0], c[1]):
            assert {n.id for n in s.cluster.nodes} == old_ids, s.node.id
            assert s.state == "NORMAL", s.node.id


# ---------------------------------------------------------------------------
# import durability reporting (r2 advisor low)
# ---------------------------------------------------------------------------


def test_import_reports_partial_application():
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("du")
        api.create_field("du", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 9 for s in range(12)]
        full = http_json(
            "POST",
            f"{c[0].node.uri}/index/du/field/f/import",
            {"rows": [0] * len(cols), "cols": cols},
        )
        assert full["applied"] == full["expected"] and not full["errors"]
        c[2].stop()
        partial = http_json(
            "POST",
            f"{c[0].node.uri}/index/du/field/f/import",
            {"rows": [1] * len(cols), "cols": cols},
            timeout=120,
        )
        assert partial["applied"] < partial["expected"]
        assert partial["errors"]
        # reads still correct from live owners
        (cnt,) = c[0].api.query("du", "Count(Row(f=1))")
        assert cnt == len(cols)


# ---------------------------------------------------------------------------
# BSI depth + wire bounds (r2 advisor low)
# ---------------------------------------------------------------------------


def test_bsi_rejects_over_32_bit_ranges():
    from pilosa_tpu.core.field import Field

    with pytest.raises(ValueError, match="BSI supports at most 32"):
        Field(None, "i", "v", FieldOptions(type="int", min=0, max=1 << 40))
    # 32-bit magnitude range is fine
    Field(None, "i", "v", FieldOptions(type="int", min=0, max=(1 << 32) - 1))
    # wide but base-centered range is fine too
    Field(
        None, "i", "v",
        FieldOptions(type="int", min=(1 << 40), max=(1 << 40) + 100),
    )


def test_wire_encode_enforces_decode_bound(monkeypatch):
    from pilosa_tpu.server import wire

    monkeypatch.setattr(wire, "_MAX_ARRAY_BYTES", 64)
    ok = wire.encode_arrays(np.arange(8, dtype=np.uint64))
    assert wire.decode_arrays(ok, 1)[0].tolist() == list(range(8))
    with pytest.raises(ValueError, match="chunk the transfer"):
        wire.encode_arrays(np.arange(9, dtype=np.uint64))


def test_remove_dead_node_succeeds():
    """Removing a crashed member must work — the freeze cannot require an
    ack from the node being removed (it may be dead; that is the point of
    remove-node)."""
    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        api = c[0].api
        api.create_index("dd")
        api.create_field("dd", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 4 for s in range(16)]
        api.import_bits("dd", "f", [0] * len(cols), cols)
        c[2].stop()  # crash, no clean leave
        uri = c[0].node.uri
        http_json(
            "POST", f"{uri}/cluster/resize/remove-node", {"id": c[2].node.id}
        )
        wait_job(uri, timeout=60)
        for s in [c[0], c[1]]:
            assert len(s.cluster.nodes) == 2
            (cnt,) = s.api.query("dd", "Count(Row(f=0))")
            assert cnt == len(cols), s.node.id
