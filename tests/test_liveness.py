"""Round-4 cluster plane: continuous liveness detection + durable topology.

Reference parity targets: gossip/gossip.go:364-443 (continuous membership
events), cluster.go:1724-1752 (confirm-down /status probes),
cluster.go:1657-1692 (.topology persistence), holder.go:599-621 (.id), and
api.go:101-105 (DEGRADED keeps the NORMAL method set).
"""

import json
import socket
import time
import urllib.request

from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.server.client import ClientError
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def http_json(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def wait_job(uri, want="DONE", timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = http_json("GET", f"{uri}/cluster/resize/job")
        if job["state"] != "RUNNING":
            assert job["state"] == want, job
            return job
        time.sleep(0.05)
    raise AssertionError("resize job did not finish")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _wait_for(predicate, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"{what} not reached within {timeout}s")


# ---------------------------------------------------------------------------
# probing
# ---------------------------------------------------------------------------


def test_probe_peers_is_concurrent():
    """Several dead peers cost ~one probe timeout, not one each
    (VERDICT r3 weak #5: serial probe_peers)."""
    with ClusterHarness(4, in_memory=True) as c:

        def slow_dead_status(uri, timeout=None, **kw):
            time.sleep(0.4)
            raise ClientError("injected: dead")

        c[0].client.status = slow_dead_status
        t0 = time.monotonic()
        alive = c[0].probe_peers()
        dt = time.monotonic() - t0
        assert dt < 0.95, f"3 dead peers serialized: {dt:.2f}s"
        assert alive["node0"] is True
        assert [alive[f"node{i}"] for i in (1, 2, 3)] == [False] * 3
        # 3 of 4 down at replica_n=1: reads are no longer safe
        assert c[0].state == "DOWN"


def test_liveness_flips_degraded_and_keeps_serving():
    """Kill one node while the cluster idles: the coordinator's probe loop
    notices within ~2x the interval, broadcasts DEGRADED, and both reads
    and writes keep working (api.go:104)."""
    with ClusterHarness(
        3, replica_n=2, in_memory=True, probe_interval=0.2
    ) as c:
        api = c[0].api
        api.create_index("lv")
        api.create_field("lv", "f", {"type": "set"})
        cols = [s * SHARD_WIDTH + 1 for s in range(12)]
        api.import_bits("lv", "f", [0] * len(cols), cols)
        assert c[0].state == "NORMAL"
        c.stop_node(2)
        # no query, no resize — the background loop alone must notice
        _wait_for(
            lambda: c[0].state == "DEGRADED", 2.0, "coordinator DEGRADED"
        )
        # ...and broadcast it to the other member
        _wait_for(lambda: c[1].state == "DEGRADED", 2.0, "peer DEGRADED")
        assert c[1].cluster.node_by_id("node2").state == "DOWN"
        # reads fail over to live replicas
        (cnt,) = c[0].api.query("lv", "Count(Row(f=0))")
        assert cnt == len(cols)
        # writes are still allowed in DEGRADED (reference api.go:104)
        api.import_bits("lv", "f", [1], [5])
        (cnt1,) = c[0].api.query("lv", "Count(Row(f=1))")
        assert cnt1 == 1


def test_liveness_recovers_to_normal():
    """A node marked DOWN that answers probes again flips the cluster back
    to NORMAL automatically."""
    with ClusterHarness(
        3, replica_n=2, in_memory=True, probe_interval=0.2
    ) as c:
        c[0].set_node_state("node1", "DOWN")
        assert c[0].state == "DEGRADED"
        _wait_for(lambda: c[0].state == "NORMAL", 2.0, "back to NORMAL")
        assert c[0].cluster.node_by_id("node1").state == "READY"


def test_degraded_blocks_schema_deletes():
    """Creates in DEGRADED are repairable on rejoin (additive schema push);
    deletes are not — a down node would never learn them — so they are
    refused until the cluster is whole again (deliberate deviation from
    api.go:104, which leaves the delete unrepaired)."""
    import pytest

    from pilosa_tpu.server.api import DisabledError

    with ClusterHarness(3, replica_n=2, in_memory=True) as c:
        c[0].api.create_index("dd")
        c[0].api.create_field("dd", "f", {"type": "set"})
        c[0].set_node_state("node2", "DOWN")
        assert c[0].state == "DEGRADED"
        with pytest.raises(DisabledError, match="delete_field"):
            c[0].api.delete_field("dd", "f")
        with pytest.raises(DisabledError, match="delete_index"):
            c[0].api.delete_index("dd")
        # creates stay allowed — the rejoin repair channel covers them
        c[0].api.create_field("dd", "f2", {"type": "set"})
        # whole again: deletes work
        c[0].set_node_state("node2", "READY")
        assert c[0].state == "NORMAL"
        c[0].api.delete_field("dd", "f")
        c[0].api.delete_index("dd")


def test_schema_repair_on_rejoin():
    """DDL issued while a node is DOWN reaches it when it recovers: the
    probe pass pushes the full schema on the DOWN->READY transition (the
    reference replays schema via gossip NodeStatus, gossip.go:295-362).
    Without this, DEGRADED-mode DDL would diverge the down node forever."""
    with ClusterHarness(
        3, replica_n=2, in_memory=True, probe_interval=0.2
    ) as c:
        c[0].api.create_index("rj")
        c[0].api.create_field("rj", "f0", {"type": "set"})
        c.stop_node(2)
        _wait_for(lambda: c[0].state == "DEGRADED", 3.0, "DEGRADED")
        # schema DDL while node2 is down (allowed in DEGRADED, api.go:104)
        c[0].api.create_field("rj", "f1", {"type": "set"})
        c[0].api.create_index("rj2")
        srv = c.restart_node(2)
        _wait_for(lambda: c[0].state == "NORMAL", 3.0, "back to NORMAL")

        def repaired():
            idx = srv.holder.index("rj")
            return (
                idx is not None
                and idx.field("f1") is not None
                and srv.holder.index("rj2") is not None
            )

        _wait_for(repaired, 3.0, "schema repaired on rejoin")
        # and the rejoined node is a full member again
        assert {n.id for n in srv.cluster.nodes} == {"node0", "node1", "node2"}


def test_probe_pass_defers_to_resize():
    """The liveness tick must not fight the resize job's status flow."""
    with ClusterHarness(2, in_memory=True) as c:
        c[0].state = "RESIZING"
        assert c[0].run_probe_pass() is False
        c[0].state = "NORMAL"


# ---------------------------------------------------------------------------
# durable identity + topology
# ---------------------------------------------------------------------------


def test_node_id_persisted(tmp_path):
    d = str(tmp_path / "n0")
    s = NodeServer(d, "original-id").start()
    s.stop()
    s2 = NodeServer(d, "different-id").start()
    try:
        assert s2.node.id == "original-id"
    finally:
        s2.stop()


def test_topology_file_lifecycle(tmp_path):
    """Multi-node membership persists to .topology; a reset to standalone
    (join rollback / removal) forgets it so flags seed the next boot."""
    s = NodeServer(str(tmp_path / "a"), "a").start()
    try:
        me = Node(id="a", uri=s.node.uri, is_coordinator=True)
        s.set_topology([me, Node(id="b", uri="http://localhost:1")])
        path = tmp_path / "a" / ".topology"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert {n["id"] for n in doc["nodes"]} == {"a", "b"}
        assert doc["replicaN"] == s.cluster.replica_n
        s.set_topology([me])
        assert not path.exists()
    finally:
        s.stop()


def test_resized_cluster_restarts_from_disk(tmp_path):
    """The VERDICT r3 done-criterion: 3-node cluster grows to 4, every
    process dies, all four restart with NO cluster flags (and wrong default
    ids) — the cluster reforms with the post-resize topology from .topology
    /.id and serves all data."""
    c = ClusterHarness(3, replica_n=2, base_dir=str(tmp_path))
    joiner = NodeServer(str(tmp_path / "node3"), "node3", replica_n=2).start()
    cols = [s * SHARD_WIDTH + 9 for s in range(24)]
    try:
        api = c[0].api
        api.create_index("pt")
        api.create_field("pt", "f", {"type": "set"})
        api.import_bits("pt", "f", [0] * len(cols), cols)
        uri = c[0].node.uri
        http_json(
            "POST", f"{uri}/cluster/join",
            {"id": joiner.node.id, "uri": joiner.node.uri},
        )
        wait_job(uri)
        assert len(c[0].cluster.nodes) == 4
        ports = {
            s.node.id: int(s.node.uri.rsplit(":", 1)[1])
            for s in [c[0], c[1], c[2], joiner]
        }
    finally:
        joiner.stop()
        c.close()  # base_dir is caller-owned: data files survive

    all_ids = {"node0", "node1", "node2", "node3"}
    revived = []
    try:
        for nid in sorted(all_ids):
            revived.append(
                NodeServer(
                    str(tmp_path / nid),
                    f"wrong-{nid}",  # .id on disk must win
                    bind=f"localhost:{ports[nid]}",
                ).start()
            )
        for s in revived:
            assert s.topology_restored, s.node.id
            assert {n.id for n in s.cluster.nodes} == all_ids, s.node.id
            assert s.cluster.replica_n == 2
            assert s.node.id in all_ids  # identity from .id, not the arg
        coords = [s for s in revived if s.node.is_coordinator]
        assert [s.node.id for s in coords] == ["node0"]
        for s in revived:
            (cnt,) = s.api.query("pt", "Count(Row(f=0))")
            assert cnt == len(cols), s.node.id
    finally:
        for s in revived:
            s.stop()


def test_cli_flags_seed_then_disk_wins(tmp_path):
    """`--cluster-hosts` seeds the first boot; after membership is on disk
    a reboot ignores (changed) flags instead of reverting the cluster."""
    from pilosa_tpu.cli.config import Config
    from pilosa_tpu.cli.main import cmd_server

    port = _free_port()
    data_dir = str(tmp_path / "n")

    def boot(peer: str) -> "NodeServer":
        cfg = Config.load(
            overrides={
                "data_dir": data_dir,
                "bind": f"localhost:{port}",
                "node_id": "n1",
                "cluster": {
                    "hosts": f"n1@http://localhost:{port},"
                    f"{peer}@http://localhost:9",
                    "probe_interval": 0,
                },
            },
        )
        return cmd_server(cfg, wait=False)

    srv = boot("n2")
    assert {n.id for n in srv.cluster.nodes} == {"n1", "n2"}
    srv.stop()
    srv2 = boot("n3")  # changed flags: must NOT take effect
    try:
        assert srv2.topology_restored
        assert {n.id for n in srv2.cluster.nodes} == {"n1", "n2"}
    finally:
        srv2.stop()


def test_cli_flags_heal_peer_uris(tmp_path):
    """Membership comes from disk, but a peer moved to a new address gets
    its URI healed from the (updated) flags — without this an operator
    could never re-address a node in a persisted cluster."""
    from pilosa_tpu.cli.config import Config
    from pilosa_tpu.cli.main import cmd_server

    port = _free_port()
    data_dir = str(tmp_path / "h")

    def boot(peer_uri: str):
        cfg = Config.load(
            overrides={
                "data_dir": data_dir,
                "bind": f"localhost:{port}",
                "node_id": "h1",
                "cluster": {
                    "hosts": f"h1@http://localhost:{port},h2@{peer_uri}",
                    "probe_interval": 0,
                },
            },
        )
        return cmd_server(cfg, wait=False)

    srv = boot("http://localhost:9")
    srv.stop()
    srv2 = boot("http://localhost:10")  # h2 moved
    try:
        assert srv2.topology_restored
        assert srv2.cluster.node_by_id("h2").uri == "http://localhost:10"
    finally:
        srv2.stop()


def test_cli_disk_id_overrides_flag_id_for_own_address(tmp_path):
    """A --cluster-hosts entry naming THIS address under a different id
    must not create a phantom second member: the durable .id wins."""
    from pilosa_tpu.cli.config import Config
    from pilosa_tpu.cli.main import cmd_server

    port = _free_port()
    data_dir = str(tmp_path / "p")
    # first boot standalone: writes .id=oldid (no .topology — single node)
    solo = NodeServer(data_dir, "oldid").start()
    solo.stop()
    cfg = Config.load(
        overrides={
            "data_dir": data_dir,
            "bind": f"localhost:{port}",
            "node_id": "newid",
            "cluster": {
                "hosts": f"newid@http://localhost:{port},"
                "other@http://localhost:9",
                "probe_interval": 0,
            },
        },
    )
    srv = cmd_server(cfg, wait=False)
    try:
        assert srv.node.id == "oldid"
        assert {n.id for n in srv.cluster.nodes} == {"oldid", "other"}
    finally:
        srv.stop()
