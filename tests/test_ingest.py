"""Bulk-ingest fast path tests (ISSUE 5 tentpole).

The write path is now staged: Field.import_bits routes non-mutex SET
batches through View.stage_bulk -> Fragment.stage_positions, which WAL-
frames the batch and defers the row-store merge + rank-cache
reconciliation to the next read barrier. These tests pin down:

- bit-for-bit equivalence of the staged path vs naive per-bit semantics,
  with reads interleaved between write batches (every read barrier must
  merge first),
- the vectorized clear path and the C-speed mutex-vector maintenance,
- WAL crash-recovery equivalence under the batched framing (satellite):
  bulk-import, "kill" before snapshot, replay, bit-identical fragment and
  identical rank-cache TopN order,
- api.import_bits summary semantics + the argsort-shared timestamp
  grouping (satellite),
- the import-roaring handler's shard/boolean param coercion (satellite):
  garbage -> 400 JSON naming the parameter, never a 500.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.field import Field, FieldOptions
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness


def _pairs_set(field, n_shards):
    """{(row, absolute_col)} across every standard-view fragment."""
    out = set()
    v = field.view("standard")
    if v is None:
        return out
    for s in v.available_shards():
        rows, cols = v.fragments[s].pairs()
        base = s * SHARD_WIDTH
        out.update(
            (int(r), int(c) + base) for r, c in zip(rows.tolist(), cols.tolist())
        )
    return out


class TestStagedFastPath:
    def test_matches_naive_semantics_with_duplicates(self):
        h = Holder().open()
        idx = h.create_index("ing")
        f = idx.create_field("f", FieldOptions())
        rng = np.random.default_rng(11)
        n = 5000
        rows = rng.integers(0, 40, n).astype(np.uint64)
        cols = rng.integers(0, 7 * SHARD_WIDTH, n).astype(np.uint64)
        # duplicates on purpose: every position twice
        f.import_bits(np.concatenate([rows, rows]), np.concatenate([cols, cols]))
        want = set(zip(rows.tolist(), cols.tolist()))
        assert _pairs_set(f, 7) == want

    def test_reads_between_batches_see_staged_bits(self):
        h = Holder().open()
        idx = h.create_index("ing2")
        f = idx.create_field("f", FieldOptions())
        f.import_bits(np.array([3, 3], np.uint64), np.array([7, SHARD_WIDTH + 9], np.uint64))
        v = f.view("standard")
        frag0 = v.fragments[0]
        # every read barrier must merge the pending delta first
        assert frag0.has_row(3)
        assert frag0.contains(3, 7)
        assert frag0.row_count(3) == 1
        assert v.fragments[1].row_count(3) == 1
        assert frag0.cache_top()[0] == (3, 1)
        # a second staged batch after the merge
        f.import_bits(np.array([3], np.uint64), np.array([8], np.uint64))
        assert frag0.row_count(3) == 2
        assert set(frag0.row_positions(3).tolist()) == {7, 8}

    def test_interleaved_clear_flushes_pending_first(self):
        h = Holder().open()
        idx = h.create_index("ing3")
        f = idx.create_field("f", FieldOptions())
        f.import_bits(np.array([1, 1, 1], np.uint64), np.array([5, 6, 7], np.uint64))
        # clear rides the exact path, which must merge the staged bits
        # before computing changed counts
        assert f.clear_bit(1, 6)
        assert not f.clear_bit(1, 99)  # never set
        f.import_bits(np.array([1], np.uint64), np.array([6], np.uint64))
        assert _pairs_set(f, 1) == {(1, 5), (1, 6), (1, 7)}

    def test_bulk_clear_sparse_and_dense_rows(self):
        h = Holder().open()
        idx = h.create_index("ing4")
        f = idx.create_field("f", FieldOptions())
        rng = np.random.default_rng(7)
        rows = rng.integers(0, 6, 4000).astype(np.uint64)
        cols = rng.integers(0, SHARD_WIDTH, 4000).astype(np.uint64)
        f.import_bits(rows, cols)
        # densify row 0 (beyond the n_words crossover)
        wide = np.arange(SHARD_WIDTH // 16, dtype=np.uint64) * 8
        f.import_bits(np.zeros(len(wide), np.uint64), wide)
        want = set(zip(rows.tolist(), cols.tolist()))
        want |= {(0, int(c)) for c in wide.tolist()}
        # clear a mixed batch: some set, some never-set, dense + sparse rows
        crows = rng.integers(0, 6, 1500).astype(np.uint64)
        ccols = rng.integers(0, SHARD_WIDTH, 1500).astype(np.uint64)
        frag = f.view("standard").fragments[0]
        n_cleared = frag.import_positions(
            None, crows * np.uint64(SHARD_WIDTH) + ccols
        )[1]
        gone = set(zip(crows.tolist(), ccols.tolist()))
        assert n_cleared == len(want & gone)
        assert _pairs_set(f, 1) == want - gone
        # rank cache reconciled in the same batch
        for r in range(6):
            assert frag.cache.get(r) == frag.row_count(r)

    def test_mutex_field_keeps_last_write_wins(self):
        h = Holder().open()
        idx = h.create_index("ing5")
        f = idx.create_field("m", FieldOptions(type="mutex", cache_type="none"))
        rows = np.array([1, 2, 3, 2], np.uint64)
        cols = np.array([4, 4, 9, 9], np.uint64)
        f.import_bits(rows, cols)
        assert _pairs_set(f, 1) == {(2, 4), (2, 9)}
        # the C-speed mutex-vector update must agree with the stored bits
        frag = f.view("standard").fragments[0]
        assert frag._mutex_map == {4: 2, 9: 2}


class TestWalCrashRecovery:
    def test_batched_framing_replay_equivalence(self, tmp_path):
        """Satellite: bulk-import, kill before snapshot, replay — bit-for-
        bit fragment equality and identical rank-cache TopN order."""
        path = os.path.join(str(tmp_path), "frag0")
        frag = Fragment(path, "i", "f", "standard", 0, max_op_n=10**9).open()
        rng = np.random.default_rng(3)
        for _ in range(4):  # several staged batches -> several WAL records
            pos = (
                rng.integers(0, 50, 3000).astype(np.uint64) * np.uint64(SHARD_WIDTH)
                + rng.integers(0, SHARD_WIDTH, 3000).astype(np.uint64)
            )
            frag.stage_positions(pos)
        # one exact import call: its set AND clear records land as ONE
        # batched WAL write (append_many)
        to_set = np.array([60 * SHARD_WIDTH + 5, 60 * SHARD_WIDTH + 6], np.uint64)
        to_clear = np.array([60 * SHARD_WIDTH + 6, 2 * SHARD_WIDTH + 1], np.uint64)
        frag.import_positions(to_set, to_clear)
        live_pairs = frag.pairs()
        live_top = frag.cache_top()
        # crash: NO close(), NO snapshot — a second Fragment replays the WAL
        assert os.path.getsize(frag.wal_path) > 0
        re = Fragment(path, "i", "f", "standard", 0, max_op_n=10**9).open()
        got_pairs = re.pairs()
        assert np.array_equal(got_pairs[0], live_pairs[0])
        assert np.array_equal(got_pairs[1], live_pairs[1])
        assert re.cache_top() == live_top
        re.close()
        frag.close()

    def test_snapshot_merges_pending_before_wal_truncate(self, tmp_path):
        """A snapshot taken with a pending delta must not lose it: the
        merge happens before truncate() discards the WAL records."""
        path = os.path.join(str(tmp_path), "frag1")
        frag = Fragment(path, "i", "f", "standard", 0, max_op_n=10**9).open()
        frag.stage_positions(np.array([5 * SHARD_WIDTH + 2], np.uint64))
        frag.snapshot()
        assert os.path.getsize(frag.wal_path) == 0
        frag.close()
        re = Fragment(path, "i", "f", "standard", 0).open()
        assert re.contains(5, 2)
        re.close()


class TestApiImport:
    def test_summary_and_timestamp_grouping(self):
        with ClusterHarness(1, in_memory=True) as c:
            api = c[0].api
            api.create_index("ti")
            api.create_field(
                "ti", "t", {"type": "time", "time_quantum": "YMD"}
            )
            cols = [3, SHARD_WIDTH + 4, 5, SHARD_WIDTH + 6]
            ts = [
                "2019-01-02T00:00",
                "2020-03-04T00:00",
                "2019-01-02T00:00",
                None,
            ]
            summary = api.import_bits("ti", "t", [1, 1, 2, 2], cols, timestamps=ts)
            assert summary["applied"] == summary["expected"] == 2  # 2 shards
            assert summary["errors"] == []
            f = c[0].holder.index("ti").field("t")
            # timestamps rode the argsort permutation: each bit landed in
            # its own day view, in the right shard
            v = f.view("standard_20190102")
            assert v is not None
            assert v.fragments[0].contains(1, 3)
            assert v.fragments[0].contains(2, 5)
            assert 1 not in v.fragments
            v2 = f.view("standard_20200304")
            assert v2.fragments[1].contains(1, SHARD_WIDTH + 4)
            # the None-timestamp bit is standard-view only
            std = f.view("standard")
            assert std.fragments[1].contains(2, SHARD_WIDTH + 6)
            for vname, vv in f.views.items():
                if vname.startswith("standard_"):
                    for frag in vv.fragments.values():
                        assert not frag.contains(2, SHARD_WIDTH + 6)

    def test_parallel_replica_routing_reaches_all_owners(self):
        with ClusterHarness(3, replica_n=2, in_memory=True) as c:
            api = c[0].api
            api.create_index("pr")
            api.create_field("pr", "f", {"type": "set"})
            rng = np.random.default_rng(5)
            cols = rng.integers(0, 6 * SHARD_WIDTH, 500).astype(np.uint64)
            summary = api.import_bits("pr", "f", [0] * len(cols), cols)
            assert summary["applied"] == summary["expected"]
            assert summary["errors"] == []
            want = int(len(np.unique(cols)))
            # every node answers the full count (each shard on 2 owners,
            # queries fan out over live owners)
            for srv in c.nodes:
                got = srv.api.query("pr", "Count(Row(f=0))")[0]
                assert got == want

    def test_ingest_stats_emitted(self):
        with ClusterHarness(1, in_memory=True) as c:
            api = c[0].api
            api.create_index("st")
            api.create_field("st", "f", {"type": "set"})
            api.import_bits("st", "f", [1, 1], [3, SHARD_WIDTH + 4])
            snap = c[0].stats.registry.snapshot()
            assert snap.get("ingest.bits;index:st") == 2
            assert snap.get("ingest.batches;index:st") == 2
            assert "ingest.apply_ms;index:st" in snap
            assert "ingest.route_ms;index:st" in snap


class TestRoaringParamCoercion:
    def test_bad_shard_and_bool_params_400(self):
        with ClusterHarness(1, in_memory=True) as c:
            uri = c[0].node.uri
            c[0].api.create_index("rc")
            c[0].api.create_field("rc", "f", {"type": "set"})

            def expect_400(method, url, body=b""):
                req = urllib.request.Request(url, data=body, method=method)
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=10)
                assert ei.value.code == 400, url
                msg = json.loads(ei.value.read())["error"]
                ei.value.close()
                return msg

            msg = expect_400(
                "POST", f"{uri}/index/rc/field/f/import-roaring/abc"
            )
            assert "shard" in msg and "abc" in msg
            msg = expect_400(
                "POST", f"{uri}/index/rc/field/f/import-roaring/0?clear=ture"
            )
            assert "clear" in msg and "ture" in msg
            msg = expect_400(
                "POST", f"{uri}/index/rc/field/f/import-roaring/0?remote=2"
            )
            assert "remote" in msg
            msg = expect_400(
                "GET", f"{uri}/index/rc/field/f/export-roaring/1.5", None
            )
            assert "shard" in msg
