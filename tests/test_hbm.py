"""HBM residency manager tests (ISSUE 4 tentpole): extent-granular
paging, pinning, prefetch, gauges, and the /debug/pprof satellite.

The acceptance property: with an HBM budget BELOW a query's working set,
the second run of the same query re-uploads only the evicted extents'
bytes — never the whole stack set (the 30-40x hbm_evict cliff from
BENCH_r05 was exactly whole-set re-staging per query).
"""

import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DEVICE_CACHE
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.hbm import residency as hbm_res
from pilosa_tpu.hbm.prefetch import Prefetcher
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.pql import parse
from pilosa_tpu.sched.admission import AdmissionController
from pilosa_tpu.server.node import NodeServer
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW


@pytest.fixture
def paging_env():
    """Single-device staging (no mesh), clean extent stats, restored
    budget/extent-rows — the deterministic environment the paging
    assertions need."""
    old_mesh = pmesh.active_mesh()
    pmesh.set_active_mesh(None)
    old_budget = DEVICE_CACHE.budget_bytes
    old_rows = hbm_res.extent_rows()
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    yield
    hbm_res.configure(extent_rows=old_rows)
    DEVICE_CACHE.budget_bytes = old_budget
    DEVICE_CACHE.clear()
    hbm_res.reset_stats()
    pmesh.set_active_mesh(old_mesh)


def _populated_executor(n_rows: int, n_shards: int, index: str = "hbmx"):
    h = Holder().open()
    idx = h.create_index(index)
    f = idx.create_field("f", FieldOptions())
    rng = np.random.default_rng(5)
    for r in range(n_rows):
        for s in range(n_shards):
            f.import_row_words(
                r, s, rng.integers(0, 2**32, WORDS_PER_ROW).astype(np.uint32)
            )
    return Executor(h), h


class TestExtentPaging:
    def test_partial_restage_under_budget_pressure(self, paging_env):
        """THE acceptance test: budget one-quarter short of the working
        set -> run 2 re-uploads exactly the deficit, not the full set."""
        row_bytes = WORDS_PER_ROW * 4
        S, EXT_ROWS, N_ROWS = 8, 2, 8
        hbm_res.configure(extent_rows=EXT_ROWS)
        ext_bytes = EXT_ROWS * row_bytes
        stack_bytes = S * row_bytes  # 4 extents per row stack
        ws = N_ROWS * stack_bytes  # 32 extents
        budget = 24 * ext_bytes  # holds 24 of 32 extents
        # the executor's _stack_guard chunks stacks over budget/4; the
        # geometry must keep one stack under that so lowering stays whole
        assert stack_bytes <= budget // 4
        DEVICE_CACHE.budget_bytes = budget

        ex, _h = _populated_executor(N_ROWS, S)
        q = (
            "Count(Union("
            + ", ".join(f"Row(f={r})" for r in range(N_ROWS))
            + "))"
        )
        # evicted_extent_bytes / restage_bytes are CUMULATIVE process
        # counters: assert on deltas, not absolutes
        snap0 = hbm_res.stats_snapshot()
        got1 = ex.execute("hbmx", q)[0]
        snap1 = hbm_res.stats_snapshot()
        deficit = ws - budget
        # cold run staged the whole working set ...
        assert snap1["restage_bytes"] - snap0["restage_bytes"] == ws
        # ... and settling back under budget evicted exactly the deficit
        evicted1 = (
            snap1["evicted_extent_bytes"] - snap0["evicted_extent_bytes"]
        )
        assert evicted1 == deficit
        assert DEVICE_CACHE.bytes_used <= budget
        # no pins survive the dispatch
        assert snap1["pinned_bytes"] == 0

        from pilosa_tpu.core.resultcache import RESULT_CACHE

        RESULT_CACHE.reset()  # run 2 must exercise extent re-staging
        got2 = ex.execute("hbmx", q)[0]
        assert got2 == got1
        snap2 = hbm_res.stats_snapshot()
        restage2 = snap2["restage_bytes"] - snap1["restage_bytes"]
        # the acceptance inequality: re-staged bytes on run 2 are bounded
        # by the evicted extents' bytes — and equal the deficit exactly
        assert restage2 <= evicted1
        assert restage2 == deficit
        assert restage2 < ws // 2  # nowhere near whole-set churn

    def test_resident_budget_means_zero_restage(self, paging_env):
        """Budget >= working set: the second run uploads nothing."""
        hbm_res.configure(extent_rows=2)
        DEVICE_CACHE.budget_bytes = 1 << 30
        ex, _h = _populated_executor(4, 8)
        q = "Count(Union(Row(f=0), Row(f=1), Row(f=2), Row(f=3)))"
        ex.execute("hbmx", q)
        snap1 = hbm_res.stats_snapshot()
        ex.execute("hbmx", q)
        snap2 = hbm_res.stats_snapshot()
        assert snap2["restage_bytes"] == snap1["restage_bytes"]

    def test_extent_and_monolithic_results_agree(self, paging_env):
        """Extent-assembled operands must be bit-identical to monolithic
        staging — same counts whatever the paging granularity."""
        DEVICE_CACHE.budget_bytes = 1 << 30
        ex, _h = _populated_executor(3, 7)
        q = "Count(Intersect(Row(f=0), Row(f=1)))Count(Xor(Row(f=1), Row(f=2)))"
        hbm_res.configure(extent_rows=0)  # monolithic
        DEVICE_CACHE.clear()
        want = ex.execute("hbmx", q)
        for rows in (1, 2, 3, 16):
            hbm_res.configure(extent_rows=rows)
            DEVICE_CACHE.clear()
            assert ex.execute("hbmx", q) == want, f"extent_rows={rows}"

    def test_write_invalidates_extents(self, paging_env):
        """A write to a covered fragment must invalidate the row's extent
        set — the next query sees the new bits, not a stale slice."""
        hbm_res.configure(extent_rows=2)
        DEVICE_CACHE.budget_bytes = 1 << 30
        ex, h = _populated_executor(1, 8)
        f = h.index("hbmx").field("f")
        f.set_bit(5, 0)
        assert ex.execute("hbmx", "Count(Row(f=5))")[0] == 1
        # second write lands in a DIFFERENT shard: only stale extents may
        # be served if invalidation missed — the count would stay 1
        f.set_bit(5, 2 * SHARD_WIDTH + 7)
        assert ex.execute("hbmx", "Count(Row(f=5))")[0] == 2

    def test_dirty_extent_single_shard_write(self, paging_env):
        """ISSUE 5 acceptance: warm an 8-extent stack, write ONE bit into
        one shard, re-run the count — the restage delta is exactly the
        covering extent's bytes (not the whole stack), and the result
        matches a cold full re-stage."""
        hbm_res.configure(extent_rows=1)  # 8 shards -> 8 extents
        DEVICE_CACHE.budget_bytes = 1 << 30
        S = 8
        ex, h = _populated_executor(1, S)
        q = "Count(Row(f=0))"
        got1 = ex.execute("hbmx", q)[0]
        snap1 = hbm_res.stats_snapshot()
        # warm repeat: fully resident, zero restage
        assert ex.execute("hbmx", q)[0] == got1
        snap2 = hbm_res.stats_snapshot()
        assert snap2["restage_bytes"] == snap1["restage_bytes"]

        f = h.index("hbmx").field("f")
        changed = f.set_bit(0, 3 * SHARD_WIDTH + 11)  # one bit, shard 3
        got2 = ex.execute("hbmx", q)[0]
        assert got2 == got1 + (1 if changed else 0)  # results stay exact
        snap3 = hbm_res.stats_snapshot()
        delta = snap3["restage_bytes"] - snap2["restage_bytes"]
        ext_bytes = 1 * WORDS_PER_ROW * 4
        stack_bytes = S * WORDS_PER_ROW * 4
        # the acceptance equality: ONLY the covering extent re-staged
        assert delta == ext_bytes
        assert delta < stack_bytes
        # equality vs a cold run: full re-stage computes the same count
        DEVICE_CACHE.clear()
        assert ex.execute("hbmx", q)[0] == got2

    def test_dirty_extent_bulk_ingest_other_row(self, paging_env):
        """A staged bulk import into OTHER rows of two shards no longer
        re-stages even the covering extents: the merge barrier's
        reconciliation (ISSUE 9) patches the resident extents in place
        to the post-merge version keys — the written row is not part of
        the operand, so the patch is a pure re-key with ZERO PCIe bytes
        (the invalidate+restage baseline paid one full extent per
        touched shard)."""
        import numpy as np

        hbm_res.configure(extent_rows=1)
        DEVICE_CACHE.budget_bytes = 1 << 30
        S = 8
        ex, h = _populated_executor(1, S)
        q = "Count(Row(f=0))"
        got1 = ex.execute("hbmx", q)[0]
        snap1 = hbm_res.stats_snapshot()
        f = h.index("hbmx").field("f")
        # staged fast path: bits for row 9 into shards 2 and 5
        f.import_bits(
            np.array([9, 9], np.uint64),
            np.array([2 * SHARD_WIDTH + 1, 5 * SHARD_WIDTH + 1], np.uint64),
        )
        assert ex.execute("hbmx", q)[0] == got1  # row 0 unchanged
        snap2 = hbm_res.stats_snapshot()
        delta = snap2["restage_bytes"] - snap1["restage_bytes"]
        baseline = 2 * WORDS_PER_ROW * 4  # invalidate+restage: two extents
        assert delta == 0, delta  # patched in place: nothing re-shipped
        assert delta < baseline
        assert (
            snap2["extent_patches"] - snap1["extent_patches"] == 2
        )  # one per covering extent
        # equality vs a cold full re-stage
        DEVICE_CACHE.clear()
        assert ex.execute("hbmx", q)[0] == got1

    def test_extent_patch_same_row_content(self, paging_env):
        """ISSUE 9 acceptance: a staged write INTO the warm operand's own
        row is patched into the resident extent ON DEVICE (old words |
        merged delta, re-keyed to the post-merge version) — the query
        sees the new bits with ZERO restage bytes, where the
        invalidate+restage baseline re-shipped the covering extent."""
        import numpy as np

        hbm_res.configure(extent_rows=4)  # 8 shards -> 2 extents
        DEVICE_CACHE.budget_bytes = 1 << 30
        S = 8
        ex, h = _populated_executor(1, S)
        q = "Count(Row(f=0))"
        got1 = ex.execute("hbmx", q)[0]
        snap1 = hbm_res.stats_snapshot()
        f = h.index("hbmx").field("f")
        # two fresh bits in row 0, shard 3: word 0 and a mid-row word
        frag3 = f.view("standard").fragments[3]
        w = frag3.row_words(0).copy()
        free = [
            int(i) * 32 + int(np.flatnonzero((w[i] & (1 << np.arange(32))) == 0)[0])
            for i in np.flatnonzero(w != 0xFFFFFFFF)[:2]
        ]
        f.import_bits(
            np.zeros(len(free), np.uint64),
            np.array([3 * SHARD_WIDTH + c for c in free], np.uint64),
        )
        got2 = ex.execute("hbmx", q)[0]
        assert got2 == got1 + len(free)  # the patched words carry the bits
        snap2 = hbm_res.stats_snapshot()
        assert snap2["restage_bytes"] == snap1["restage_bytes"]  # no PCIe re-stage
        assert snap2["extent_patches"] - snap1["extent_patches"] == 1
        # equality vs a cold full re-stage of the patched stack
        DEVICE_CACHE.clear()
        assert ex.execute("hbmx", q)[0] == got2

    def test_subset_barrier_preserves_other_shards_patchability(
        self, paging_env
    ):
        """A barrier over a SUBSET of shards must not invalidate (or
        forget) still-patchable extents covering OTHER dirty shards: a
        query population reading shards 0-3 under sustained ingest into
        shards 0-7 would otherwise silently defeat in-place patching
        for the 4-7 population (code-review finding on ISSUE 9)."""
        import numpy as np

        hbm_res.configure(extent_rows=4)  # 8 shards -> 2 extents
        DEVICE_CACHE.budget_bytes = 1 << 30
        S = 8
        ex, h = _populated_executor(1, S)
        q = "Count(Row(f=0))"
        got1 = ex.execute("hbmx", q)[0]  # both extents resident
        snap1 = hbm_res.stats_snapshot()
        f = h.index("hbmx").field("f")
        v = f.view("standard")
        # stage row-9 bits into BOTH extents' shards
        f.import_bits(
            np.array([9, 9], np.uint64),
            np.array([1 * SHARD_WIDTH + 1, 5 * SHARD_WIDTH + 1], np.uint64),
        )
        # subset barrier: only shard 1's fragment (extent 0)
        v.sync_pending(frags=[v.fragments[1]])
        assert 5 in v._dirty_staged  # shard 5 stays remembered
        # shard 5's own barrier still patches its extent in place
        v.sync_pending(frags=[v.fragments[5]])
        assert ex.execute("hbmx", q)[0] == got1
        snap2 = hbm_res.stats_snapshot()
        assert snap2["restage_bytes"] == snap1["restage_bytes"], (
            "subset barrier forced an extent re-stage"
        )
        assert snap2["extent_patches"] - snap1["extent_patches"] == 2
        DEVICE_CACHE.clear()
        assert ex.execute("hbmx", q)[0] == got1

    def test_cost_discount_scoped_to_referenced_fields(self, paging_env):
        """Field f's warm residency discounts f-queries only — a cold
        query on field g keeps its full admission byte weight."""
        from pilosa_tpu.core.field import FieldOptions
        from pilosa_tpu.sched import cost as costmod

        hbm_res.configure(extent_rows=2)
        DEVICE_CACHE.budget_bytes = 1 << 30
        ex, h = _populated_executor(2, 8)  # field "f"
        idx = h.index("hbmx")
        g = idx.create_field("g", FieldOptions())
        g.set_bit(1, 7)
        shards = list(range(8))
        cold_g = costmod.estimate(idx, parse("Count(Row(g=1))"), shards)
        cold_f = costmod.estimate(idx, parse("Count(Row(f=0))"), shards)
        assert cold_g.device_bytes > 0
        ex.execute("hbmx", "Count(Row(f=0))")  # f's stack now resident
        warm_f = costmod.estimate(idx, parse("Count(Row(f=0))"), shards)
        cold_g2 = costmod.estimate(idx, parse("Count(Row(g=1))"), shards)
        assert warm_f.device_bytes < cold_f.device_bytes  # f discounted
        assert cold_g2.device_bytes == cold_g.device_bytes  # g untouched

    def test_prefetch_warm_then_hit(self, paging_env):
        """A warm pass staged under prefetching() marks its extents;
        the real query's staging then counts prefetch hits."""
        hbm_res.configure(extent_rows=2)
        DEVICE_CACHE.budget_bytes = 1 << 30
        ex, _h = _populated_executor(2, 8)
        q = "Count(Intersect(Row(f=0), Row(f=1)))"
        with hbm_res.prefetching():
            warmed = ex.warm("hbmx", parse(q))
        assert warmed == 1
        snap = hbm_res.stats_snapshot()
        assert snap["prefetch_staged"] >= 8  # 2 stacks x 4 extents
        assert snap["prefetch_hits"] == 0
        ex.execute("hbmx", q)
        snap2 = hbm_res.stats_snapshot()
        assert snap2["prefetch_hits"] >= 8
        # warm staged it all: the query itself uploaded nothing new
        assert snap2["restage_bytes"] == snap["restage_bytes"]


class TestPrefetcher:
    def test_runs_offered_tasks(self):
        p = Prefetcher(depth=4).start()
        try:
            done = threading.Event()
            p.offer(done.set)
            assert done.wait(5)
        finally:
            p.stop()

    def test_bounded_queue_drops_oldest(self):
        p = Prefetcher(depth=1).start()
        try:
            gate = threading.Event()
            first_running = threading.Event()
            ran: list = []

            def blocker():
                first_running.set()
                gate.wait(5)

            p.offer(blocker)
            assert first_running.wait(5)
            # worker busy: these contend for the single queue slot
            p.offer(lambda: ran.append("a"))
            p.offer(lambda: ran.append("b"))
            gate.set()
            deadline = time.monotonic() + 5
            while not p.idle() and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.05)  # let the last popped task finish
            assert p.dropped == 1
            assert ran == ["b"]  # oldest queued offer was shed
        finally:
            p.stop()

    def test_task_errors_are_swallowed(self):
        msgs: list = []
        p = Prefetcher(depth=2, logger=msgs.append).start()
        try:
            done = threading.Event()
            p.offer(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
            p.offer(done.set)
            assert done.wait(5)
            assert any("boom" in m for m in msgs)
        finally:
            p.stop()

    def test_admission_queue_peek_feeds_prefetcher(self):
        """maybe_prefetch offers ONLY when a new arrival would wait."""

        class FakePrefetcher:
            def __init__(self):
                self.offers = []

            def offer(self, warm):
                self.offers.append(warm)
                return True

        ctl = AdmissionController(max_concurrent=1, queue_depth=4)
        fake = ctl.prefetcher = FakePrefetcher()
        assert not ctl.maybe_prefetch(lambda: None)  # idle: no offer
        t = ctl.admit()
        try:
            assert ctl.maybe_prefetch(lambda: None)  # saturated: offered
            assert len(fake.offers) == 1
            assert not ctl.maybe_prefetch(None)  # no warm closure
        finally:
            t.release()
        assert not ctl.maybe_prefetch(lambda: None)  # idle again


class TestServerIntegration:
    @pytest.fixture()
    def server(self):
        srv = NodeServer(None, "hbm-srv", hbm_prefetch_depth=4)
        srv.start()
        yield srv
        srv.stop()

    def test_hbm_gauges_exported_on_metrics(self, server):
        api = server.api
        api.create_index("hg")
        api.create_field("hg", "f")
        f = server.holder.index("hg").field("f")
        rng = np.random.default_rng(1)
        for s in range(4):
            f.import_row_words(
                1, s, rng.integers(0, 2**32, WORDS_PER_ROW).astype(np.uint32)
            )
        assert api.query("hg", "Count(Row(f=1))")[0] > 0
        with urllib.request.urlopen(
            f"{server.node.uri}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        for gauge in (
            "pilosa_tpu_hbm_resident_extents",
            "pilosa_tpu_hbm_pinned_bytes",
            "pilosa_tpu_hbm_restage_bytes",
            "pilosa_tpu_hbm_prefetch_hits",
        ):
            assert gauge in text, gauge
        # a query ran: at least one extent-marked operand is resident
        line = next(
            ln
            for ln in text.splitlines()
            if ln.startswith("pilosa_tpu_hbm_resident_extents ")
        )
        assert float(line.split()[-1]) >= 1

    def test_debug_pprof_profiles_live_queries(self, server):
        api = server.api
        api.create_index("pi")
        api.create_field("pi", "f")
        f = server.holder.index("pi").field("f")
        rng = np.random.default_rng(2)
        for s in range(2):
            f.import_row_words(
                1, s, rng.integers(0, 2**32, WORDS_PER_ROW).astype(np.uint32)
            )
        api.query("pi", "Count(Row(f=1))")  # warm compile
        out = {}

        def capture():
            with urllib.request.urlopen(
                f"{server.node.uri}/debug/pprof?seconds=1", timeout=30
            ) as resp:
                out["text"] = resp.read().decode()

        t = threading.Thread(target=capture)
        t.start()
        # keep queries flowing through the whole capture window
        while t.is_alive():
            api.query("pi", "Count(Row(f=1))")
        t.join(10)
        text = out["text"]
        assert "cProfile capture" in text
        assert "(no queries executed" not in text
        # pstats table header + a function from the query path
        assert "cumulative" in text
        assert "query_response" in text or "execute_response" in text

    def test_debug_pprof_rejects_bad_seconds(self, server):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{server.node.uri}/debug/pprof?seconds=abc", timeout=10
            )
        assert ei.value.code == 400
