"""Device cache budget tests (VERDICT round-1 task 3: bound HBM residency).

The reference bounds storage residency via mmap + syswrap caps
(/root/reference/syswrap/mmap.go, roaring.go:1437 RemapRoaringStorage);
here the analog is the byte-budgeted LRU over device arrays — now the
extent store for the HBM residency manager (pilosa_tpu/hbm/): builds are
single-flight, entries can be pinned (eviction deferred), and invalidation
of a pinned entry keeps its bytes on the ledger until the last unpin.
"""

import threading
import time

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DEVICE_CACHE, DeviceCache, new_owner_token
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW


class TestDeviceCacheUnit:
    def test_lru_eviction_under_budget(self):
        c = DeviceCache(budget_bytes=1000)
        t = new_owner_token()
        for i in range(10):
            c.put((t, i), np.zeros(64, np.uint32))  # 256 B each
        assert c.bytes_used <= 1000
        # oldest entries evicted, newest kept
        assert c.get((t, 9)) is not None
        assert c.get((t, 0)) is None
        assert c.evictions > 0

    def test_get_refreshes_recency(self):
        c = DeviceCache(budget_bytes=600)
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))
        c.put((t, 1), np.zeros(64, np.uint32))
        c.get((t, 0))  # refresh 0
        c.put((t, 2), np.zeros(64, np.uint32))  # evicts 1, not 0
        assert c.get((t, 0)) is not None
        assert c.get((t, 1)) is None

    def test_oversized_entry_admitted(self):
        c = DeviceCache(budget_bytes=100)
        t = new_owner_token()
        big = np.zeros(1000, np.uint32)
        c.put((t, "big"), big)
        assert c.get((t, "big")) is not None  # admitted to serve the query
        c.put((t, "next"), np.zeros(8, np.uint32))
        assert c.bytes_used <= 4032 + 100  # big evicted once anything lands

    def test_owner_invalidation(self):
        c = DeviceCache(budget_bytes=10_000)
        t1, t2 = new_owner_token(), new_owner_token()
        c.put((t1, 0), np.zeros(8, np.uint32))
        c.put((t1, 1), np.zeros(8, np.uint32))
        c.put((t2, 0), np.zeros(8, np.uint32))
        c.invalidate_owner(t1)
        assert c.get((t1, 0)) is None and c.get((t1, 1)) is None
        assert c.get((t2, 0)) is not None

    def test_replacement_accounting(self):
        c = DeviceCache(budget_bytes=10_000)
        t = new_owner_token()
        c.put((t, 0), np.zeros(100, np.uint32))
        c.put((t, 0), np.zeros(50, np.uint32))
        assert c.bytes_used == 200


class TestSingleFlightBuilds:
    def test_concurrent_get_or_build_runs_one_build(self):
        """Satellite acceptance: two threads get_or_build the same key ->
        exactly one build runs and the byte ledger never overshoots."""
        c = DeviceCache(budget_bytes=1 << 20)
        t = new_owner_token()
        builds = []
        entered = threading.Event()
        release = threading.Event()

        def build():
            builds.append(threading.current_thread().name)
            entered.set()
            release.wait(5)  # hold the build open so peers must wait
            return np.zeros(64, np.uint32)  # 256 B

        results = {}

        def worker(name):
            results[name] = c.get_or_build((t, "k"), build)

        threads = [
            threading.Thread(target=worker, args=(f"w{i}",), name=f"w{i}")
            for i in range(4)
        ]
        threads[0].start()
        assert entered.wait(5)
        for th in threads[1:]:
            th.start()
        time.sleep(0.05)  # let the waiters park on the build condition
        release.set()
        for th in threads:
            th.join(5)
        assert len(builds) == 1  # exactly one build process-wide
        assert c.bytes_used == 256  # no double-charge on the ledger
        arrs = list(results.values())
        assert all(a is arrs[0] for a in arrs)  # everyone shares the result

    def test_failed_build_releases_the_flight(self):
        c = DeviceCache(budget_bytes=1 << 20)
        t = new_owner_token()

        def boom():
            raise RuntimeError("build failed")

        with pytest.raises(RuntimeError):
            c.get_or_build((t, "k"), boom)
        # the key is not wedged: a later build succeeds
        arr = c.get_or_build((t, "k"), lambda: np.zeros(8, np.uint32))
        assert arr is not None
        assert c.bytes_used == 32


class TestPinning:
    def test_pinned_entry_survives_eviction_pressure(self):
        """Satellite acceptance: eviction during a pinned dispatch is
        deferred — the pinned entry is never dropped mid-flight."""
        c = DeviceCache(budget_bytes=1000)
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))  # 256 B
        assert c.pin_if_present((t, 0))
        for i in range(1, 12):
            c.put((t, i), np.zeros(64, np.uint32))
        assert c.get((t, 0)) is not None  # pinned: deferred, not evicted
        assert c.stats_snapshot()["pinned_bytes"] == 256
        c.unpin((t, 0))
        # unpin settles the deferred debt: back under budget
        assert c.bytes_used <= 1000

    def test_pin_refcounts_nest(self):
        c = DeviceCache(budget_bytes=1000)
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))
        assert c.pin_if_present((t, 0))
        assert c.pin_if_present((t, 0))
        c.unpin((t, 0))
        # still pinned once: pressure must not evict it
        for i in range(1, 12):
            c.put((t, i), np.zeros(64, np.uint32))
        assert c.get((t, 0)) is not None
        c.unpin((t, 0))

    def test_invalidate_while_pinned_keeps_bytes_until_unpin(self):
        """An in-flight operand's memory is genuinely held even after a
        write invalidates its entry: lookup misses immediately, the byte
        ledger releases only at the last unpin (zombie accounting)."""
        c = DeviceCache(budget_bytes=10_000)
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))
        assert c.pin_if_present((t, 0))
        c.invalidate_owner(t)
        assert c.get((t, 0)) is None  # new queries rebuild
        assert c.bytes_used == 256  # bytes still accounted (in flight)
        assert c.stats_snapshot()["pinned_bytes"] == 256
        c.unpin((t, 0))
        assert c.bytes_used == 0
        assert c.stats_snapshot()["pinned_bytes"] == 0

    def test_stale_pin_safety_valve(self):
        """A leaked pin older than pin_timeout is forcibly released by
        the evictor instead of wedging the budget forever."""
        clock = [0.0]
        c = DeviceCache(
            budget_bytes=1000, pin_timeout=5.0, clock=lambda: clock[0]
        )
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))
        assert c.pin_if_present((t, 0))  # never unpinned: the "leak"
        clock[0] = 10.0  # past the timeout
        for i in range(1, 12):
            c.put((t, i), np.zeros(64, np.uint32))
        assert c.get((t, 0)) is None  # reclaimed and evicted
        assert c.stats_snapshot()["stale_pin_reclaims"] == 1
        assert c.bytes_used <= 1000

    def test_deferred_eviction_session(self):
        """deferred_eviction() suspends budget settling until the session
        exits (the lowering's whole-operand-set staging window)."""
        c = DeviceCache(budget_bytes=1000)
        t = new_owner_token()
        with c.deferred_eviction():
            for i in range(12):
                c.put((t, i), np.zeros(64, np.uint32))
            assert c.bytes_used == 12 * 256  # transiently over budget
            assert len(c) == 12
        assert c.bytes_used <= 1000  # settled on exit
        assert c.get((t, 11)) is not None  # LRU tail kept, head dropped
        assert c.get((t, 0)) is None


class TestFragmentUnderBudget:
    def test_topn_row_counts_stay_under_budget(self):
        """Open a many-row fragment, run batched row counts (the TopN pass-2
        shape), and assert device residency never exceeds the budget."""
        old_budget = DEVICE_CACHE.budget_bytes
        row_bytes = WORDS_PER_ROW * 4
        n_rows = 512
        budget = 32 * row_bytes  # fits 32 of 512 rows
        DEVICE_CACHE.budget_bytes = budget
        try:
            f = Fragment(None, "i", "f", "standard", 0)
            f.open()
            rng = np.random.default_rng(0)
            rows = rng.integers(0, n_rows, 20_000).astype(np.uint64)
            cols = rng.integers(0, SHARD_WIDTH, 20_000).astype(np.uint64)
            f.bulk_import(rows, cols)
            ids = f.row_ids()
            assert len(ids) == n_rows
            counts = f.row_counts(ids, chunk=16)
            assert DEVICE_CACHE.bytes_used <= budget + 16 * row_bytes
            # correctness unaffected by eviction
            want = np.array([f.row_count(r) for r in ids], np.uint64)
            np.testing.assert_array_equal(counts, want)
        finally:
            DEVICE_CACHE.budget_bytes = old_budget

    def test_mutation_invalidates_then_rebuilds(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.open()
        f.set_bit(3, 100)
        before = int(np.asarray(f.row_device(3)).sum())
        f.set_bit(3, 200)
        arr = np.asarray(f.row_device(3))
        from pilosa_tpu.ops.bitmap import unpack_positions

        assert set(unpack_positions(arr).tolist()) == {100, 200}
        assert before != 0
