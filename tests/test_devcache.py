"""Device cache budget tests (VERDICT round-1 task 3: bound HBM residency).

The reference bounds storage residency via mmap + syswrap caps
(/root/reference/syswrap/mmap.go, roaring.go:1437 RemapRoaringStorage);
here the analog is the byte-budgeted LRU over device arrays.
"""

import numpy as np
import pytest

from pilosa_tpu.core.devcache import DEVICE_CACHE, DeviceCache, new_owner_token
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW


class TestDeviceCacheUnit:
    def test_lru_eviction_under_budget(self):
        c = DeviceCache(budget_bytes=1000)
        t = new_owner_token()
        for i in range(10):
            c.put((t, i), np.zeros(64, np.uint32))  # 256 B each
        assert c.bytes_used <= 1000
        # oldest entries evicted, newest kept
        assert c.get((t, 9)) is not None
        assert c.get((t, 0)) is None
        assert c.evictions > 0

    def test_get_refreshes_recency(self):
        c = DeviceCache(budget_bytes=600)
        t = new_owner_token()
        c.put((t, 0), np.zeros(64, np.uint32))
        c.put((t, 1), np.zeros(64, np.uint32))
        c.get((t, 0))  # refresh 0
        c.put((t, 2), np.zeros(64, np.uint32))  # evicts 1, not 0
        assert c.get((t, 0)) is not None
        assert c.get((t, 1)) is None

    def test_oversized_entry_admitted(self):
        c = DeviceCache(budget_bytes=100)
        t = new_owner_token()
        big = np.zeros(1000, np.uint32)
        c.put((t, "big"), big)
        assert c.get((t, "big")) is not None  # admitted to serve the query
        c.put((t, "next"), np.zeros(8, np.uint32))
        assert c.bytes_used <= 4032 + 100  # big evicted once anything lands

    def test_owner_invalidation(self):
        c = DeviceCache(budget_bytes=10_000)
        t1, t2 = new_owner_token(), new_owner_token()
        c.put((t1, 0), np.zeros(8, np.uint32))
        c.put((t1, 1), np.zeros(8, np.uint32))
        c.put((t2, 0), np.zeros(8, np.uint32))
        c.invalidate_owner(t1)
        assert c.get((t1, 0)) is None and c.get((t1, 1)) is None
        assert c.get((t2, 0)) is not None

    def test_replacement_accounting(self):
        c = DeviceCache(budget_bytes=10_000)
        t = new_owner_token()
        c.put((t, 0), np.zeros(100, np.uint32))
        c.put((t, 0), np.zeros(50, np.uint32))
        assert c.bytes_used == 200


class TestFragmentUnderBudget:
    def test_topn_row_counts_stay_under_budget(self):
        """Open a many-row fragment, run batched row counts (the TopN pass-2
        shape), and assert device residency never exceeds the budget."""
        old_budget = DEVICE_CACHE.budget_bytes
        row_bytes = WORDS_PER_ROW * 4
        n_rows = 512
        budget = 32 * row_bytes  # fits 32 of 512 rows
        DEVICE_CACHE.budget_bytes = budget
        try:
            f = Fragment(None, "i", "f", "standard", 0)
            f.open()
            rng = np.random.default_rng(0)
            rows = rng.integers(0, n_rows, 20_000).astype(np.uint64)
            cols = rng.integers(0, SHARD_WIDTH, 20_000).astype(np.uint64)
            f.bulk_import(rows, cols)
            ids = f.row_ids()
            assert len(ids) == n_rows
            counts = f.row_counts(ids, chunk=16)
            assert DEVICE_CACHE.bytes_used <= budget + 16 * row_bytes
            # correctness unaffected by eviction
            want = np.array([f.row_count(r) for r in ids], np.uint64)
            np.testing.assert_array_equal(counts, want)
        finally:
            DEVICE_CACHE.budget_bytes = old_budget

    def test_mutation_invalidates_then_rebuilds(self):
        f = Fragment(None, "i", "f", "standard", 0)
        f.open()
        f.set_bit(3, 100)
        before = int(np.asarray(f.row_device(3)).sum())
        f.set_bit(3, 200)
        arr = np.asarray(f.row_device(3))
        from pilosa_tpu.ops.bitmap import unpack_positions

        assert set(unpack_positions(arr).tolist()) == {100, 200}
        assert before != 0
