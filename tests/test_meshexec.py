"""Mesh-group execution tests (ISSUE 10): topology membership, the
group-spanning lowering's differential equivalence against both the HTTP
fan-out path and the naive set model, the 1-dispatch/1-read acceptance
counters, the batcher's lowering-class round split, and the
collective-cost admission terms.

Runs on the tier-1 virtual 8-device mesh (conftest force_cpu(8)); the
16/32-device certification lives in tools/mesh_cert.py (CI mesh job)."""

import threading

import numpy as np
import pytest

from pilosa_tpu.cluster.topology import Cluster, JumpHasher, Node
from pilosa_tpu.core.naive import NaiveBitmap
from pilosa_tpu.core.resultcache import RESULT_CACHE
from pilosa_tpu.exec import meshgroup
from pilosa_tpu.exec import plan as planmod
from pilosa_tpu.exec.batcher import CountBatcher
from pilosa_tpu.parallel import mesh as pmesh
from pilosa_tpu.pql import parse
from pilosa_tpu.sched import cost as costmod
from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness

N_SHARDS = 6


# ---------------------------------------------------------------------------
# topology membership
# ---------------------------------------------------------------------------


def test_node_mesh_group_json_roundtrip():
    n = Node(id="a", uri="http://h:1", mesh_group="ici0")
    assert Node.from_json(n.to_json()).mesh_group == "ici0"
    # absent key (pre-mesh peer) degrades to no group
    assert Node.from_json({"id": "b"}).mesh_group == ""


def test_cluster_mesh_peers():
    c = Cluster(
        nodes=[
            Node(id="a", mesh_group="g1"),
            Node(id="b", mesh_group="g1"),
            Node(id="c", mesh_group="g2"),
            Node(id="d"),
            Node(id="e", mesh_group="g1", state="DOWN"),
        ],
        hasher=JumpHasher(),
    )
    assert c.mesh_group_of("a") == "g1"
    assert c.mesh_group_of("zzz") == ""
    peers = {n.id for n in c.mesh_peers("a")}
    assert peers == {"b"}  # not self, not g2, not groupless, not DOWN
    assert c.mesh_peers("d") == []


def test_registry_register_unregister():
    gen0 = pmesh.group_generation()
    pmesh.register_group_member("tg", "n1", "h1")
    try:
        assert pmesh.group_members("tg") == {"n1": "h1"}
        assert pmesh.registered_group_of("n1") == "tg"
        assert pmesh.group_generation() > gen0
    finally:
        pmesh.unregister_group_member("tg", "n1")
    assert pmesh.group_members("tg") == {}
    assert pmesh.registered_group_of("n1") == ""


# ---------------------------------------------------------------------------
# eligibility
# ---------------------------------------------------------------------------


def test_eligibility_gates():
    ok = parse("Count(Intersect(Row(f=1), Row(f=2)))").calls[0]
    assert meshgroup.eligible(ok)
    assert meshgroup.eligible(parse("TopN(f, Row(f=2), n=3)").calls[0])
    # Shift's cross-shard carry may read predecessors outside the group
    assert not meshgroup.eligible(parse("Count(Shift(Row(f=1), n=1))").calls[0])
    # time ranges walk the coordinator's view list only
    assert not meshgroup.eligible(
        parse("Row(f=1, from='2020-01-01T00:00', to='2020-02-01T00:00')").calls[0]
    )
    # BSI aggregates fold since the plane-streamed lowering (round 11):
    # their in-program reductions partition into the mesh collective
    assert meshgroup.eligible(parse("Sum(field=v)").calls[0])
    assert meshgroup.eligible(parse("Min(field=v)").calls[0])
    assert meshgroup.eligible(parse("Max(Row(f=1), field=v)").calls[0])
    # a Shift-bearing filter child still disqualifies the whole call
    assert not meshgroup.eligible(
        parse("Sum(Shift(Row(f=1), n=1), field=v)").calls[0]
    )


# ---------------------------------------------------------------------------
# differential equivalence on a real 3-node one-group cluster
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh_cluster():
    with ClusterHarness(
        3, in_memory=True, mesh_group="test-ici",
        telemetry_sample_interval=0.0,
    ) as cluster:
        api = cluster[0].api
        api.create_index("mx")
        api.create_field("mx", "f")
        api.create_field(
            "mx", "v", options={"type": "int", "min": -500, "max": 500}
        )
        rng = np.random.default_rng(7)
        cols = {}
        for r in range(1, 5):
            c = rng.integers(
                0, N_SHARDS * SHARD_WIDTH, 4000
            ).astype(np.uint64)
            api.import_bits("mx", "f", np.full(len(c), r, np.uint64), c)
            cols[r] = c
        vcols = np.unique(
            rng.integers(0, N_SHARDS * SHARD_WIDTH, 2000).astype(np.uint64)
        )
        vvals = rng.integers(-500, 501, len(vcols)).astype(np.int64)
        api.import_values("mx", "v", vcols, vvals)
        yield cluster, cols, (vcols, vvals)


def _set_mesh(cluster, on: bool) -> None:
    for node in cluster.nodes:
        node.executor.mesh_min_nodes = 2 if on else 0


def _both(cluster, pql, index="mx"):
    """(mesh-path results, HTTP-fan-out results, mesh stats delta)."""
    api = cluster[0].api
    _set_mesh(cluster, True)
    meshgroup.reset_stats()
    r_mesh = api.query(index, pql)
    snap = meshgroup.stats_snapshot()
    _set_mesh(cluster, False)
    try:
        r_http = api.query(index, pql)
    finally:
        _set_mesh(cluster, True)
    return r_mesh, r_http, snap


class TestDifferentialEquivalence:
    def test_count_shapes_vs_http_and_naive(self, mesh_cluster):
        cluster, cols, _ = mesh_cluster
        na = {r: NaiveBitmap(c.tolist()) for r, c in cols.items()}
        shapes = [
            (
                "Count(Intersect(Row(f=1), Row(f=2)))",
                na[1].intersect(na[2]).count(),
            ),
            ("Count(Union(Row(f=1), Row(f=2)))", na[1].union(na[2]).count()),
            (
                "Count(Difference(Row(f=1), Row(f=3)))",
                na[1].difference(na[3]).count(),
            ),
            ("Count(Xor(Row(f=2), Row(f=4)))", na[2].xor(na[4]).count()),
        ]
        for pql, want in shapes:
            (got_mesh,), (got_http,), snap = _both(cluster, pql)
            assert got_mesh == got_http == want, (pql, got_mesh, got_http, want)
            assert snap["dispatches"] == 1 and snap["fallbacks"] == 0, (pql, snap)

    def test_row_results_vs_http_and_naive(self, mesh_cluster):
        cluster, cols, _ = mesh_cluster
        na = {r: NaiveBitmap(c.tolist()) for r, c in cols.items()}
        (rm,), (rh,), snap = _both(cluster, "Union(Row(f=1), Row(f=2))")
        want = na[1].union(na[2]).slice()
        assert sorted(rm.columns().tolist()) == sorted(rh.columns().tolist())
        assert sorted(rm.columns().tolist()) == want
        assert snap["dispatches"] == 1, snap

    def test_bsi_condition_count(self, mesh_cluster):
        cluster, _, (vcols, vvals) = mesh_cluster
        (gm,), (gh,), snap = _both(cluster, "Count(Row(v > 100))")
        assert gm == gh == int((vvals > 100).sum())
        assert snap["dispatches"] == 1, snap

    def test_not_count(self, mesh_cluster):
        cluster, cols, (vcols, _) = mesh_cluster
        exists = set()
        for c in cols.values():
            exists.update(c.tolist())
        exists.update(vcols.tolist())
        (gm,), (gh,), snap = _both(cluster, "Count(Not(Row(f=1)))")
        assert gm == gh == len(exists - set(cols[1].tolist()))
        assert snap["dispatches"] == 1, snap

    def test_topn_plain_and_filtered(self, mesh_cluster):
        cluster, _, _ = mesh_cluster
        for pql in ("TopN(f, n=3)", "TopN(f, Row(f=2), n=3)"):
            (pm,), (ph,), _ = _both(cluster, pql)
            assert [(p.id, p.count) for p in pm] == [
                (p.id, p.count) for p in ph
            ], pql

    def test_topn_tally_not_stale_after_member_write(self, mesh_cluster):
        """Regression: the filtered-TopN tally bundle is cached under the
        GROUP view's owner token, which no member write ever eagerly
        invalidates — only the versions salted into its cache key keep it
        honest. Warm the bundle, write through a member, re-query: the
        mesh result must reflect the write and match the HTTP path."""
        cluster, _, _ = mesh_cluster
        api = cluster[0].api
        # own index: this test mutates rows, and the module fixture's
        # cols map must stay exact for the other differential tests
        api.create_index("tn")
        api.create_field("tn", "f")
        rng = np.random.default_rng(11)
        for r in (1, 2):
            c = rng.integers(0, N_SHARDS * SHARD_WIDTH, 3000).astype(np.uint64)
            api.import_bits("tn", "f", np.full(len(c), r, np.uint64), c)
        _set_mesh(cluster, True)
        pql = "TopN(f, Row(f=2), n=5)"
        (warm,) = api.query("tn", pql)  # populate the group tally bundle
        # land a bit present in BOTH row 1 and the filter row 2, on a
        # shard another member owns, so the (1 ∩ 2) tally must move
        col = 4 * SHARD_WIDTH + 99_999
        api.query("tn", f"Set({col}, f=1)Set({col}, f=2)")
        (pm,) = api.query("tn", pql)
        _set_mesh(cluster, False)
        try:
            (ph,) = api.query("tn", pql)
        finally:
            _set_mesh(cluster, True)
        assert [(p.id, p.count) for p in pm] == [(p.id, p.count) for p in ph]
        by_id = {p.id: p.count for p in pm}
        warm_by_id = {p.id: p.count for p in warm}
        assert by_id[1] == warm_by_id.get(1, 0) + 1, (warm, pm)

    def test_every_coordinator_agrees(self, mesh_cluster):
        """Any member may coordinate a mesh-group query, not just node 0."""
        cluster, cols, _ = mesh_cluster
        na = NaiveBitmap(cols[1].tolist()).intersect(
            NaiveBitmap(cols[2].tolist())
        )
        _set_mesh(cluster, True)
        for node in cluster.nodes:
            (got,) = node.api.query(
                "mx", "Count(Intersect(Row(f=1), Row(f=2)))"
            )
            assert got == na.count()

    def test_write_visible_through_mesh_path(self, mesh_cluster):
        """A write landing after a warm mesh query re-keys the covering
        group stack: the next mesh query sees it (version-keyed staging,
        never served stale)."""
        cluster, _, _ = mesh_cluster
        api = cluster[0].api
        _set_mesh(cluster, True)
        (before,) = api.query("mx", "Count(Row(f=9))")
        col = 3 * SHARD_WIDTH + 17
        api.query("mx", f"Set({col}, f=9)")
        (after,) = api.query("mx", "Count(Row(f=9))")
        assert after == before + 1
        (after_http,) = _both(cluster, "Count(Row(f=9))")[1]
        assert after_http == after


# ---------------------------------------------------------------------------
# acceptance counters: 1 compiled dispatch + 1 blocking host read,
# independent of group shard count
# ---------------------------------------------------------------------------


class TestAcceptanceCounters:
    def test_one_dispatch_one_read(self, mesh_cluster):
        cluster, cols, _ = mesh_cluster
        api = cluster[0].api
        _set_mesh(cluster, True)
        pql = "Count(Intersect(Row(f=1), Row(f=2)))"
        api.query("mx", pql)  # warm: compile + stage under this mode
        RESULT_CACHE.reset()  # the probe asserts the dispatch, not the cache
        planmod.reset_stats()
        meshgroup.reset_stats()
        (got,) = api.query("mx", pql)
        na = NaiveBitmap(cols[1].tolist()).intersect(
            NaiveBitmap(cols[2].tolist())
        )
        assert got == na.count()
        assert planmod.STATS["evals"] == 1, planmod.STATS
        assert planmod.STATS["host_reads"] == 1, planmod.STATS
        snap = meshgroup.stats_snapshot()
        assert snap["dispatches"] == 1 and snap["fallbacks"] == 0, snap
        assert snap["local_shards"] == N_SHARDS, snap

    def test_counters_independent_of_shard_count(self, mesh_cluster):
        """Twice the shards, same 1 dispatch + 1 read (the whole point:
        blocking-read count no longer scales with the group)."""
        cluster, _, _ = mesh_cluster
        api = cluster[0].api
        api.create_index("wide")
        api.create_field("wide", "f")
        rng = np.random.default_rng(3)
        for width in (4, 12):
            c = rng.integers(0, width * SHARD_WIDTH, 3000).astype(np.uint64)
            api.import_bits(
                "wide", "f", np.full(len(c), width, np.uint64), c
            )
        _set_mesh(cluster, True)
        reads = []
        for width in (4, 12):
            pql = f"Count(Row(f={width}))"
            api.query("wide", pql)  # warm
            RESULT_CACHE.reset()  # probe the dispatch, not the cache
            planmod.reset_stats()
            api.query("wide", pql)
            reads.append(
                (planmod.STATS["evals"], planmod.STATS["host_reads"])
            )
        assert reads == [(1, 1), (1, 1)], reads

    def test_multi_count_batch_one_dispatch(self, mesh_cluster):
        cluster, cols, _ = mesh_cluster
        api = cluster[0].api
        _set_mesh(cluster, True)
        pql = "Count(Row(f=1))Count(Row(f=2))Count(Xor(Row(f=1),Row(f=2)))"
        got_w = api.query("mx", pql)  # warm
        RESULT_CACHE.reset()  # probe the batch dispatch, not the cache
        planmod.reset_stats()
        got = api.query("mx", pql)
        assert got == got_w
        assert planmod.STATS["evals"] == 1, planmod.STATS
        assert planmod.STATS["host_reads"] == 1, planmod.STATS
        _set_mesh(cluster, False)
        try:
            assert api.query("mx", pql) == got
        finally:
            _set_mesh(cluster, True)


# ---------------------------------------------------------------------------
# mixed topology: the group covers only part of the query's owners
# ---------------------------------------------------------------------------


def test_group_subset_mixed_legs():
    """Nodes 0+1 share an ICI domain, node 2 does not: one mesh dispatch
    covers the group's shards, node 2's shards ride an HTTP leg, and the
    merged result is bit-identical to the all-HTTP path and the naive
    model."""
    with ClusterHarness(
        3, in_memory=True, mesh_group="sub-ici",
        telemetry_sample_interval=0.0,
    ) as cluster:
        # evict node 2 from the domain: registry + topology both drop it
        pmesh.unregister_group_member("sub-ici", cluster[2].node.id)
        cluster.nodes[2].mesh_group_name = ""
        cluster[2].node.mesh_group = ""
        cluster.sync_topology()
        api = cluster[0].api
        api.create_index("sx")
        api.create_field("sx", "f")
        rng = np.random.default_rng(5)
        a = rng.integers(0, 8 * SHARD_WIDTH, 5000).astype(np.uint64)
        b = rng.integers(0, 8 * SHARD_WIDTH, 5000).astype(np.uint64)
        api.import_bits("sx", "f", np.full(len(a), 1, np.uint64), a)
        api.import_bits("sx", "f", np.full(len(b), 2, np.uint64), b)
        na = NaiveBitmap(a.tolist()).intersect(NaiveBitmap(b.tolist()))

        # sanity: node 2 actually owns some shards of this index
        idx = cluster[0].holder.index("sx")
        owners = cluster[0].cluster.shards_by_node(
            "sx", sorted(idx.available_shards())
        )
        assert cluster[2].node.id in owners, owners

        meshgroup.reset_stats()
        (got,) = api.query("sx", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert got == na.count()
        snap = meshgroup.stats_snapshot()
        assert snap["dispatches"] == 1, snap  # nodes 0+1 folded
        total = sum(len(v) for v in owners.values())
        assert 0 < snap["local_shards"] < total, (snap, owners)

        _set_mesh(cluster, False)
        (got_http,) = api.query("sx", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert got_http == got


def test_mesh_disabled_by_min_nodes_zero(mesh_cluster):
    cluster, cols, _ = mesh_cluster
    _set_mesh(cluster, False)
    try:
        meshgroup.reset_stats()
        (got,) = cluster[0].api.query(
            "mx", "Count(Intersect(Row(f=1), Row(f=2)))"
        )
        na = NaiveBitmap(cols[1].tolist()).intersect(
            NaiveBitmap(cols[2].tolist())
        )
        assert got == na.count()
        assert meshgroup.stats_snapshot()["dispatches"] == 0
    finally:
        _set_mesh(cluster, True)


# ---------------------------------------------------------------------------
# batcher: rounds split by lowering class
# ---------------------------------------------------------------------------


class TestBatcherClassSplit:
    def _drive(self, classify):
        """Run a leader + 4 queued waiters of alternating classes through
        one batcher round; returns the merged call-name sets per execute."""
        b = CountBatcher()
        b.classify = classify
        execs = []
        release = threading.Event()

        def execute(q):
            if not release.is_set():  # the leader's own solo execution
                release.wait(5.0)
            execs.append([str(c) for c in q.calls])
            return [0] * len(q.calls)

        def leader():
            b.run("i", parse("Count(Row(a=0))"), execute)

        t = threading.Thread(target=leader)
        t.start()
        # queue waiters while the leader blocks in execute
        threads = []
        for i in range(4):
            row = "m" if i % 2 == 0 else "x"
            q = parse(f"Count(Row({row}={i}))")

            def run(q=q):
                b.run("i", q, execute)

            w = threading.Thread(target=run)
            w.start()
            threads.append(w)
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with b._mu:
                if len(b._queue.get("i", ())) == 4:
                    break
            time.sleep(0.005)
        release.set()
        t.join(10.0)
        for w in threads:
            w.join(10.0)
        return execs[1:]  # drop the leader's solo run

    def test_rounds_split_by_class(self):
        """Waiters of two lowering classes never merge into one multi-root
        execution (mesh-sharded and extent-local operand placements are
        incompatible)."""

        def classify(index, q):
            return "mesh" if "Row(m" in str(q.calls[0]) else "fanout"

        rounds = self._drive(classify)
        assert len(rounds) == 2, rounds  # one merged round per class
        for calls in rounds:
            kinds = {("m" if "Row(m" in c else "x") for c in calls}
            assert len(kinds) == 1, rounds

    def test_no_classifier_merges_one_round(self):
        rounds = self._drive(None)
        assert len(rounds) == 1 and len(rounds[0]) == 4, rounds

    def test_classifier_errors_degrade_to_shared_class(self):
        def classify(index, q):
            raise RuntimeError("boom")

        rounds = self._drive(classify)
        assert len(rounds) == 1 and len(rounds[0]) == 4, rounds


def test_count_lowering_class(mesh_cluster):
    cluster, _, _ = mesh_cluster
    ex = cluster[0].executor
    _set_mesh(cluster, True)
    assert ex.count_lowering_class("mx", parse("Count(Row(f=1))")) == "mesh"
    # Shift is mesh-ineligible -> fanout
    assert (
        ex.count_lowering_class("mx", parse("Count(Shift(Row(f=1), n=1))"))
        == "fanout"
    )
    _set_mesh(cluster, False)
    try:
        assert (
            ex.count_lowering_class("mx", parse("Count(Row(f=1))")) == "fanout"
        )
    finally:
        _set_mesh(cluster, True)


# ---------------------------------------------------------------------------
# collective-cost accounting (sched/cost.py) + admission integration
# ---------------------------------------------------------------------------


class TestCollectiveCost:
    def test_link_terms(self):
        costmod.configure_links(ici_gbps=100.0, dcn_gbps=2.0)
        try:
            # 1 GB over 100 GB/s = 10 ms; over 2 GB/s = 500 ms
            assert costmod.collective_ms(10**9, "ici") == pytest.approx(10.0)
            assert costmod.collective_ms(10**9, "dcn") == pytest.approx(500.0)
            assert costmod.collective_ms(0, "ici") == 0.0
            # leg floor charged once per fan-out, not per leg
            base = costmod.transport_ms(0, 0, 0)
            one = costmod.transport_ms(0, 1000, 1)
            three = costmod.transport_ms(0, 1000, 3)
            assert base == 0.0 and one == three > 0.0
        finally:
            costmod.configure_links(ici_gbps=100.0, dcn_gbps=3.0)

    def test_estimate_carries_transport(self, mesh_cluster):
        cluster, _, _ = mesh_cluster
        idx = cluster[0].holder.index("mx")
        q = parse("Count(Row(f=1))")
        profile = cluster[0].executor.transport_profile(idx)
        assert profile["mesh_shards"] > 0, profile
        c_mesh = costmod.estimate(idx, q, transport=profile)
        assert c_mesh.transport_ms > 0.0
        c_plain = costmod.estimate(idx, q)
        assert c_plain.transport_ms == 0.0

    def test_transport_profile_split(self):
        with ClusterHarness(
            3, in_memory=True, mesh_group="tp-ici",
            telemetry_sample_interval=0.0,
        ) as cluster:
            pmesh.unregister_group_member("tp-ici", cluster[2].node.id)
            cluster.nodes[2].mesh_group_name = ""
            cluster[2].node.mesh_group = ""
            cluster.sync_topology()
            api = cluster[0].api
            api.create_index("tp")
            api.create_field("tp", "f")
            cols = np.arange(0, 8 * SHARD_WIDTH, SHARD_WIDTH, dtype=np.uint64)
            api.import_bits("tp", "f", np.ones(len(cols), np.uint64), cols)
            idx = cluster[0].holder.index("tp")
            profile = cluster[0].executor.transport_profile(idx)
            owners = cluster[0].cluster.shards_by_node(
                "tp", sorted(idx.available_shards())
            )
            total = sum(len(v) for v in owners.values())
            # node2 left the domain: its shards (if any) are DCN legs;
            # the local node's own share crosses no link
            want_leg_shards = len(owners.get(cluster[2].node.id, []))
            assert profile["leg_shards"] == want_leg_shards, (profile, owners)
            assert profile["legs"] == (1 if want_leg_shards else 0)
            assert profile["mesh_shards"] + profile["leg_shards"] <= total

    def test_admission_honors_transport_ms(self):
        from pilosa_tpu.sched.admission import AdmissionController, ShedError
        from pilosa_tpu.sched.cost import QueryCost

        ctl = AdmissionController(max_concurrent=2)
        # transport alone exceeds the deadline: shed on arrival
        heavy = QueryCost(device_bytes=0, transport_ms=5000.0)
        with pytest.raises(ShedError):
            ctl.admit(cost=heavy, deadline=1.0)
        # same deadline without the transport bill admits
        t = ctl.admit(cost=QueryCost(device_bytes=0), deadline=1.0)
        t.release()
        # and on the leg lane too
        with pytest.raises(ShedError):
            ctl.admit(cost=heavy, deadline=1.0, leg=True)


# ---------------------------------------------------------------------------
# GC + config plumbing
# ---------------------------------------------------------------------------


def test_view_created_after_adapter_cached(mesh_cluster):
    """Regression: a field whose view materializes AFTER the group
    adapter was cached (views are created lazily on first write) must
    become visible to the mesh path — a memoized miss would pin its
    rows at zero forever while the HTTP path counts them."""
    cluster, _, _ = mesh_cluster
    api = cluster[0].api
    _set_mesh(cluster, True)
    api.create_field("mx", "late")
    # same shard assignment as the warm adapter: Count the empty field
    # first (memoizes the view resolution), then import into it
    (empty,) = api.query("mx", "Count(Row(late=1))")
    assert empty == 0
    cols = np.arange(0, 6 * SHARD_WIDTH, SHARD_WIDTH // 2, dtype=np.uint64)
    api.import_bits("mx", "late", np.ones(len(cols), np.uint64), cols)
    (got,) = api.query("mx", "Count(Row(late=1))")
    _set_mesh(cluster, False)
    try:
        (http,) = api.query("mx", "Count(Row(late=1))")
    finally:
        _set_mesh(cluster, True)
    assert got == http == len(cols), (got, http, len(cols))


def test_field_delete_recreate_drops_adapters(mesh_cluster):
    """Regression: deleting a field drops the index's cached group
    adapters — a recreate must not serve the dead Field/View objects."""
    cluster, _, _ = mesh_cluster
    api = cluster[0].api
    _set_mesh(cluster, True)
    api.create_field("mx", "reborn")
    cols = np.arange(0, 6 * SHARD_WIDTH, SHARD_WIDTH, dtype=np.uint64)
    api.import_bits("mx", "reborn", np.ones(len(cols), np.uint64), cols)
    (first,) = api.query("mx", "Count(Row(reborn=1))")
    assert first == len(cols)
    api.delete_field("mx", "reborn")
    api.create_field("mx", "reborn")
    cols2 = cols[:3]
    api.import_bits("mx", "reborn", np.ones(len(cols2), np.uint64), cols2)
    (got,) = api.query("mx", "Count(Row(reborn=1))")
    _set_mesh(cluster, False)
    try:
        (http,) = api.query("mx", "Count(Row(reborn=1))")
    finally:
        _set_mesh(cluster, True)
    assert got == http == len(cols2), (got, http)


def test_transport_floor_once_per_query():
    costmod.configure_links(ici_gbps=100.0, dcn_gbps=3.0)
    q1 = parse("Count(Row(f=1))").calls
    q20 = parse("".join(f"Count(Row(f={i}))" for i in range(20))).calls
    profile = {"mesh_shards": 0, "legs": 2, "leg_shards": 4}
    one = costmod._transport_estimate(q1, profile)
    twenty = costmod._transport_estimate(q20, profile)
    # byte terms scale with calls; the fixed round-trip floor must not
    # (legs run concurrently, adjacent Counts share a dispatch)
    floor = costmod.transport_ms(0, 0, 2)
    assert one >= floor
    assert twenty - floor < 20 * (one - floor) + 1e-9
    assert twenty < 20 * one


def test_min_nodes_one_folds_single_peer():
    """min-nodes=1 honors its documented contract: even a single
    group-local peer owner folds (saving its HTTP leg)."""
    with ClusterHarness(
        2, in_memory=True, mesh_group="mn-ici",
        telemetry_sample_interval=0.0,
    ) as cluster:
        api = cluster[0].api
        api.create_index("mn")
        api.create_field("mn", "f")
        cols = np.arange(0, 6 * SHARD_WIDTH, SHARD_WIDTH, dtype=np.uint64)
        api.import_bits("mn", "f", np.ones(len(cols), np.uint64), cols)
        for node in cluster.nodes:
            node.executor.mesh_min_nodes = 1
        meshgroup.reset_stats()
        (got,) = api.query("mn", "Count(Row(f=1))")
        assert got == len(cols)
        assert meshgroup.stats_snapshot()["dispatches"] >= 1


def test_admission_charges_full_group_shards(mesh_cluster):
    """A mesh-group dispatch stages the WHOLE group's operands on this
    device: the admission estimate must charge every folded shard, not
    the coordinator's 1/N share."""
    from pilosa_tpu.shardwidth import WORDS_PER_ROW

    cluster, _, _ = mesh_cluster
    idx = cluster[0].holder.index("mx")
    profile = cluster[0].executor.transport_profile(idx)
    assert profile["device_shards"] == profile["mesh_shards"] > 0
    q = parse("Count(Row(f=1))")
    c = costmod.estimate(
        idx, q, shard_count=profile["device_shards"], transport=profile
    )
    # one row stack over the full group shard axis (minus any warm
    # residency discount, hence >= a single-shard charge floor)
    assert c.device_bytes <= profile["device_shards"] * WORDS_PER_ROW * 4


def test_group_index_cache_drops_with_index(mesh_cluster):
    cluster, _, _ = mesh_cluster
    api = cluster[0].api
    api.create_index("gone")
    api.create_field("gone", "f")
    cols = np.arange(0, 6 * SHARD_WIDTH, SHARD_WIDTH // 2, dtype=np.uint64)
    api.import_bits("gone", "f", np.ones(len(cols), np.uint64), cols)
    _set_mesh(cluster, True)
    (got,) = api.query("gone", "Count(Row(f=1))")
    assert got == len(cols)
    with meshgroup._cache_mu:
        assert any(k[0] == "gone" for k in meshgroup._cache)
    api.delete_index("gone")
    with meshgroup._cache_mu:
        assert not any(k[0] == "gone" for k in meshgroup._cache)


def test_mesh_config_three_way():
    from pilosa_tpu.cli.config import Config

    cfg = Config.load(
        env={
            "PILOSA_TPU_MESH__GROUP": "podA",
            "PILOSA_TPU_MESH__MIN_NODES": "3",
            "PILOSA_TPU_MESH__ICI_GBPS": "186.0",
        }
    )
    assert cfg.mesh.group == "podA"
    assert cfg.mesh.min_nodes == 3
    assert cfg.mesh.ici_gbps == 186.0
    text = cfg.to_toml()
    assert "[mesh]" in text and 'group = "podA"' in text

    from pilosa_tpu.cli.main import _FLAG_KNOBS, _build_parser

    # every [mesh] knob is flag-reachable (API003-005 enforce docs sync)
    assert _FLAG_KNOBS["mesh_group"] == ("mesh", "group")
    p = _build_parser()
    args = p.parse_args(
        ["server", "--mesh-group", "podB", "--mesh-min-nodes", "2"]
    )
    assert args.mesh_group == "podB" and args.mesh_min_nodes == 2


def test_topology_carries_group_through_persistence(tmp_path):
    srv = None
    try:
        from pilosa_tpu.server.node import NodeServer

        srv = NodeServer(
            str(tmp_path / "n0"), "n0", mesh_group="persist-ici",
            telemetry_sample_interval=0.0,
        ).start()
        peer = Node(id="n1", uri="http://h:1", mesh_group="persist-ici")
        srv.set_topology([srv.node, peer])
        assert srv.cluster.mesh_group_of("n0") == "persist-ici"
        assert srv.cluster.mesh_group_of("n1") == "persist-ici"
        import json

        with open(srv._topology_path) as f:
            doc = json.load(f)
        assert {n["meshGroup"] for n in doc["nodes"]} == {"persist-ici"}
    finally:
        if srv is not None:
            srv.stop()
