"""Query flight recorder: per-stage attribution, cross-node trace
assembly, real latency histograms (ISSUE 6).

Layers: Histogram/quantile units and the Prometheus exposition linter;
tracer units (monotonic durations, deque ring, sampling, synthetic
spans); assembly (clamping, self-time, top stages); and the acceptance
scenario — a profile=true Count on a 3-node cluster returns ONE
assembled trace whose stage durations reconcile against query_ms, while
/metrics exports query_ms as a bucketed histogram with a finite p99."""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import ClusterHarness
from pilosa_tpu.utils import stats as statsmod
from pilosa_tpu.utils import tracing
from pilosa_tpu.utils.stats import Histogram

from tools.prom_lint import lint, lint_against_registry


def http_json(method, url, body=None, headers=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else {}


def _seed(api, index="fr", field="f", n_shards=6):
    api.create_index(index)
    api.create_field(index, field, {"type": "set"})
    rows, cols = [], []
    for s in range(n_shards):
        for r in range(3):
            for k in range(40):
                rows.append(r)
                cols.append(s * SHARD_WIDTH + 13 * k + r)
    api.import_bits(index, field, rows, cols)


def _walk(node):
    yield node
    for c in node.get("children", ()):
        yield from _walk(c)


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_counts_sum_min_max_exact(self):
        h = Histogram()
        for v in (0.4, 3.0, 3.0, 700.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(706.4)
        assert snap["min"] == 0.4 and snap["max"] == 700.0
        assert snap["mean"] == pytest.approx(706.4 / 4)

    def test_quantiles_clamped_to_observed_range(self):
        h = Histogram()
        for _ in range(100):
            h.observe(1.0)
        # every observation identical: all quantiles report exactly it,
        # not a bucket edge
        assert h.quantile(0.5) == 1.0
        assert h.quantile(0.99) == 1.0

    def test_quantile_orders_and_brackets(self):
        h = Histogram()
        for v in range(1, 101):  # 1..100
            h.observe(float(v))
        p50, p95, p99 = h.quantile(0.5), h.quantile(0.95), h.quantile(0.99)
        assert 1.0 <= p50 <= p95 <= p99 <= 100.0
        assert 25.0 <= p50 <= 75.0  # log buckets are coarse, not wrong
        assert p99 >= 75.0

    def test_cumulative_monotone_with_inf(self):
        h = Histogram()
        for v in (0.002, 5.0, 1e6):  # first, middle, +Inf bucket
            h.observe(v)
        cum = h.cumulative()
        counts = [c for _, c in cum]
        assert counts == sorted(counts)
        assert cum[-1][0] == float("inf") and cum[-1][1] == 3

    def test_registry_snapshot_has_quantiles(self):
        c = statsmod.StatsClient()
        for v in (0.1, 0.2, 0.3):
            c.timing("query_ms", v)
        snap = c.registry.snapshot()["query_ms"]
        for key in ("count", "sum", "mean", "min", "p50", "p95", "p99", "max"):
            assert key in snap, key
        assert c.registry.quantile("query_ms", 0.99) == snap["p99"]

    def test_prometheus_histogram_exposition_lints_clean(self):
        c = statsmod.StatsClient().with_tags("index:i1")
        for v in (0.5, 2.0, 40.0):
            c.timing("query_ms", v)
        c.count("query_n")
        text = c.registry.prometheus_text()
        assert "# TYPE pilosa_tpu_query_ms histogram" in text
        assert 'pilosa_tpu_query_ms_bucket{index="i1",le="+Inf"} 3' in text
        assert 'pilosa_tpu_query_ms_count{index="i1"} 3' in text
        assert lint_against_registry(text) == []

    def test_type_emitted_once_across_tagged_series(self):
        c = statsmod.StatsClient()
        c.with_tags("index:a").count("query_n")
        c.with_tags("index:b").count("query_n")
        text = c.registry.prometheus_text()
        assert text.count("# TYPE pilosa_tpu_query_n counter") == 1


class TestPromLint:
    DECLARED = {"query_ms", "query_n"}

    def test_clean_text_passes(self):
        text = (
            "# TYPE pilosa_tpu_query_n counter\n"
            "pilosa_tpu_query_n 3\n"
        )
        assert lint(text, declared=self.DECLARED) == []

    def test_undeclared_family_flagged(self):
        text = "# TYPE pilosa_tpu_rogue counter\npilosa_tpu_rogue 1\n"
        errs = lint(text, declared=self.DECLARED)
        assert any("not declared" in e for e in errs)

    def test_missing_type_flagged(self):
        errs = lint("pilosa_tpu_query_n 3\n", declared=self.DECLARED)
        assert any("no preceding TYPE" in e for e in errs)

    def test_duplicate_type_flagged(self):
        text = (
            "# TYPE pilosa_tpu_query_n counter\n"
            "pilosa_tpu_query_n 3\n"
            "# TYPE pilosa_tpu_query_n counter\n"
        )
        errs = lint(text, declared=self.DECLARED)
        assert any("duplicate TYPE" in e or "after its first sample" in e
                   for e in errs)

    def test_non_monotone_buckets_flagged(self):
        text = (
            "# TYPE pilosa_tpu_query_ms histogram\n"
            'pilosa_tpu_query_ms_bucket{le="1"} 5\n'
            'pilosa_tpu_query_ms_bucket{le="2"} 3\n'
            'pilosa_tpu_query_ms_bucket{le="+Inf"} 5\n'
            "pilosa_tpu_query_ms_sum 9\n"
            "pilosa_tpu_query_ms_count 5\n"
        )
        errs = lint(text, declared=self.DECLARED)
        assert any("not monotone" in e for e in errs)

    def test_inf_bucket_must_equal_count(self):
        text = (
            "# TYPE pilosa_tpu_query_ms histogram\n"
            'pilosa_tpu_query_ms_bucket{le="1"} 2\n'
            'pilosa_tpu_query_ms_bucket{le="+Inf"} 2\n'
            "pilosa_tpu_query_ms_sum 9\n"
            "pilosa_tpu_query_ms_count 5\n"
        )
        errs = lint(text, declared=self.DECLARED)
        assert any("_count" in e for e in errs)


# ---------------------------------------------------------------------------
# tracer units
# ---------------------------------------------------------------------------


class TestTracer:
    def test_duration_on_monotonic_clock_survives_wall_step(self, monkeypatch):
        tr = tracing.Tracer()
        sp = tr.start_span("t")
        real = time.time
        # NTP step: wall clock jumps an hour BACK mid-span
        monkeypatch.setattr(tracing.time, "time", lambda: real() - 3600.0)
        sp.finish()
        assert sp.duration is not None and 0.0 <= sp.duration < 5.0

    def test_ring_is_bounded_deque(self):
        from collections import deque

        tr = tracing.Tracer(keep=4)
        assert isinstance(tr._spans, deque) and tr._spans.maxlen == 4
        for i in range(10):
            tr.start_span(f"s{i}").finish()
        names = [s.name for s in tr.spans()]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_root_sampling_rate_zero_and_force(self):
        tr = tracing.Tracer(sample_rate=0.0)
        tr.start_span("root").finish()
        assert tr.spans() == []
        tr.start_span("forced", force=True).finish()
        assert [s.name for s in tr.spans()] == ["forced"]
        # an incoming trace header means the SENDER sampled: always record
        hdrs = {tracing.TRACE_HEADER: "abc", tracing.SPAN_HEADER: "def"}
        sp = tr.start_span_from_headers("cont", hdrs)
        assert sp.sampled and sp.trace_id == "abc" and sp.parent_id == "def"

    def test_children_inherit_sampling(self):
        tr = tracing.Tracer(sample_rate=0.0)
        root = tr.start_span("root")
        with root:
            assert tracing.active_span() is None  # unsampled -> inactive
            child = tracing.start_span("child")
            assert isinstance(child, tracing.NopSpan)

    def test_record_span_and_ingest_dedupe(self):
        tr = tracing.Tracer()
        with tr.start_span("root", force=True) as root:
            tracing.record_span("synth", 0.05, tags={"k": 1})
        names = {s.name for s in tr.spans()}
        assert names == {"root", "synth"}
        remote = [
            {"name": "r1", "traceId": root.trace_id, "spanId": "rs1",
             "parentId": root.span_id, "node": "n1", "start": 1.0,
             "durationMs": 2.0, "tags": {}},
        ]
        assert tr.ingest(remote) == 1
        assert tr.ingest(remote) == 0  # dedup by span id
        assert len(tr.spans_for(root.trace_id)) == 3


class TestAssembly:
    BASE = 1000.0

    def _spans(self):
        return [
            {"name": "api.query", "traceId": "t1", "spanId": "a",
             "parentId": None, "node": "n0", "start": self.BASE,
             "durationMs": 100.0, "tags": {"query_ms": 100.0}},
            {"name": "exec.dispatch", "traceId": "t1", "spanId": "b",
             "parentId": "a", "node": "n0", "start": self.BASE + 0.010,
             "durationMs": 30.0, "tags": {}},
            # completed before the parent opened (admission wait /
            # cross-node skew): must clamp, raw window preserved
            {"name": "sched.admit", "traceId": "t1", "spanId": "c",
             "parentId": "a", "node": "n0", "start": self.BASE - 0.050,
             "durationMs": 50.0, "tags": {}},
            # other trace: excluded
            {"name": "api.query", "traceId": "t2", "spanId": "z",
             "parentId": None, "node": "n0", "start": self.BASE,
             "durationMs": 1.0, "tags": {}},
        ]

    def test_clamping_and_self_time(self):
        tree = tracing.assemble(self._spans(), "t1")
        assert tree["spanCount"] == 3
        (root,) = tree["roots"]
        assert root["name"] == "api.query"
        kids = {c["name"]: c for c in root["children"]}
        admit = kids["sched.admit"]
        assert admit["durationMs"] == 0.0  # clamped into the parent
        assert admit["raw"]["durationMs"] == 50.0
        disp = kids["exec.dispatch"]
        assert disp["durationMs"] == pytest.approx(30.0)
        assert "raw" not in disp
        assert root["selfMs"] == pytest.approx(70.0)

    def test_top_stages_orders_by_self_time(self):
        tops = tracing.top_stages(self._spans(), "t1", 5)
        assert tops[0]["name"] == "api.query"
        assert tops[0]["selfMs"] == pytest.approx(70.0)
        assert {t["name"] for t in tops} == {
            "api.query", "exec.dispatch", "sched.admit"
        }


# ---------------------------------------------------------------------------
# registries stay documented
# ---------------------------------------------------------------------------


def test_observability_doc_lists_every_registered_name():
    """docs/observability.md is the enforced catalog: every STAT_NAMES
    metric and SPAN_NAMES span must appear in it (the doc-side half of
    the API001/006 registry contract)."""
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs",
        "observability.md",
    )
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    for name in sorted(statsmod.STAT_NAMES):
        assert name in text, f"STAT_NAMES entry {name!r} missing from docs"
    for prefix in sorted(statsmod.STAT_PREFIXES):
        assert prefix in text, f"STAT_PREFIXES {prefix!r} missing from docs"
    for name in sorted(tracing.SPAN_NAMES):
        assert name in text, f"SPAN_NAMES entry {name!r} missing from docs"


def test_client_error_carries_trace_id_from_headers():
    import email.message
    import io

    from pilosa_tpu.server.client import InternalClient

    h = email.message.Message()
    h["X-Pilosa-Trace-Id"] = "abc123"
    h["Retry-After"] = "1"
    e = urllib.error.HTTPError(
        "http://p:1/internal/index/i/query", 429, "shed", h,
        io.BytesIO(b'{"error":"shed"}'),
    )
    err = InternalClient()._classify(
        "POST", "http://p:1/internal/index/i/query", "http://p:1", e
    )
    assert err.trace_id == "abc123"
    assert "abc123" in str(err)
    assert err.status == 429 and err.retryable


# ---------------------------------------------------------------------------
# wired into real nodes
# ---------------------------------------------------------------------------


class TestFlightRecorderHTTP:
    def test_shed_429_names_its_trace(self):
        with ClusterHarness(
            1, in_memory=True, max_concurrent_queries=1,
            admission_queue_depth=0,
        ) as c:
            srv = c[0]
            srv.api.create_index("sh")
            srv.api.create_field("sh", "f", {"type": "set"})
            held = srv.scheduler.admit()  # occupy the only slot
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    http_json(
                        "POST", f"{srv.node.uri}/index/sh/query",
                        {"query": "Count(Row(f=0))"},
                    )
                e = ei.value
                assert e.code == 429
                body = json.loads(e.read())
                e.close()
                assert body.get("traceId"), body
                assert e.headers.get(tracing.TRACE_HEADER) == body["traceId"]
            finally:
                held.release()
            assert srv.scheduler.pending() == (0, 0)

    def test_debug_traces_assembles_one_tree(self):
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            _seed(srv.api, n_shards=2)
            r = http_json(
                "POST", f"{srv.node.uri}/index/fr/query",
                {"query": "Count(Row(f=0))", "profile": True},
            )
            prof = r.get("profile")
            assert prof and prof["roots"], r.keys()
            tid = prof["traceId"]
            tree = http_json(
                "GET", f"{srv.node.uri}/debug/traces?trace={tid}"
            )
            assert tree["traceId"] == tid
            names = {
                n["name"] for root in tree["roots"] for n in _walk(root)
            }
            assert "api.query" in names
            assert "exec.dispatch" in names

    def test_profile_forces_sampling_when_tracing_off(self):
        with ClusterHarness(1, in_memory=True, tracing_enabled=False) as c:
            srv = c[0]
            _seed(srv.api, n_shards=2)
            srv.api.query("fr", "Count(Row(f=0))")
            assert srv.tracer.spans() == []  # rate 0: nothing sampled
            resp = srv.api.query_response(
                "fr", "Count(Row(f=0))", profile=True
            )
            assert resp.profile is not None and resp.profile["roots"]

    def test_slow_query_logs_flight_record(self):
        captured = []
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            srv.long_query_time = 1e-9
            srv.logger = lambda m: captured.append(m)
            _seed(srv.api, n_shards=2)
            srv.api.query("fr", "Count(Row(f=0))")
        slow = [m for m in captured if "slow query" in m]
        assert slow
        assert any("trace=" in m for m in slow)
        assert any("top stages by self-time" in m for m in slow)

    def test_pprof_report_links_trace_ids(self):
        with ClusterHarness(1, in_memory=True) as c:
            srv = c[0]
            _seed(srv.api, n_shards=2)
            out = {}

            def capture():
                out["text"] = srv.profiler.capture(3.0)

            th = threading.Thread(target=capture, daemon=True)
            th.start()
            deadline = time.monotonic() + 5
            while not srv.profiler._active and time.monotonic() < deadline:
                time.sleep(0.01)
            srv.api.query("fr", "Count(Row(f=0))")
            srv.profiler.close()  # end the window early
            th.join(10)
            text = out["text"]
            assert "traces: " in text, text[:200]
            tid = text.split("traces: ", 1)[1].split()[0]
            # the id resolves in the flight recorder, and the profiled
            # span carries the window marker
            spans = srv.tracer.spans_for(tid)
            assert spans
            assert any(
                s["tags"].get("pprof.window") for s in spans
            )


# ---------------------------------------------------------------------------
# acceptance: 3-node profile=true reconciliation + /metrics p99
# ---------------------------------------------------------------------------


def _metrics_p99(text: str, family: str, label: str) -> float:
    """Reconstruct a p99 from the exposition's cumulative buckets."""
    buckets = []
    total = None
    for line in text.splitlines():
        if line.startswith(f"{family}_bucket") and label in line:
            le = line.split('le="', 1)[1].split('"', 1)[0]
            bound = float("inf") if le == "+Inf" else float(le)
            buckets.append((bound, float(line.rsplit(" ", 1)[1])))
        elif line.startswith(f"{family}_count") and label in line:
            total = float(line.rsplit(" ", 1)[1])
    assert buckets and total, f"no {family} histogram for {label}"
    rank = 0.99 * total
    prev_bound = 0.0
    for bound, cum in buckets:
        if cum >= rank:
            return bound if math.isfinite(bound) else prev_bound
        prev_bound = bound
    return buckets[-1][0]


def test_profile_count_reconciles_on_three_node_cluster():
    """Acceptance: profile=true Count on a 3-node cluster returns ONE
    assembled trace in which the coordinator's tagged stage self-times —
    admission wait + the slowest fan-out leg (which contains the
    executing node's staging, compiled dispatch, and host read) —
    reconcile to within 10% of the reported query_ms; /metrics exports
    query_ms as a bucketed histogram with a finite p99."""
    # cache_result_mb=0: this acceptance probes the fan-out's span tree —
    # a result-cache hit (the intended fast path) would skip the legs and
    # dispatches the reconciliation is about
    with ClusterHarness(
        3, replica_n=1, in_memory=True, cache_result_mb=0
    ) as c:
        api = c[0].api
        _seed(api, n_shards=12)
        # cold profiled run: staging attribution must be visible
        resp = api.query_response("fr", "Count(Row(f=0))", profile=True)
        assert resp.results == [480]
        cold = resp.profile
        assert cold is not None
        cold_spans = c[0].tracer.spans_for(cold["traceId"])
        stage_spans = [s for s in cold_spans if s["name"] == "exec.stage"]
        assert stage_spans, "cold run must attribute operand staging"
        assert any(
            s["tags"].get("stage.bytes", 0) > 0 for s in stage_spans
        )
        # warm up compile caches / connections, then reconcile
        for _ in range(2):
            api.query("fr", "Count(Row(f=0))")
        best = None
        for _ in range(5):
            resp = api.query_response("fr", "Count(Row(f=0))", profile=True)
            prof = resp.profile
            (root,) = prof["roots"]
            assert root["name"] == "api.query" and root["node"] == "node0"
            qms = root["tags"]["query_ms"]
            admit_ms = root["tags"]["sched.wait_ms"]
            nodes = list(_walk(root))
            legs = [n for n in nodes if n["name"] == "rpc.leg"]
            # all three nodes participated in one trace
            peers = {leg["tags"].get("peer") for leg in legs}
            assert peers == {"node0", "node1", "node2"}, peers
            # remote legs contain the remote node's own api.query span
            # (cross-node parentage intact)
            remote_children = {
                ch["node"]
                for leg in legs
                for ch in leg["children"]
                if ch["name"] == "api.query"
            }
            assert {"node1", "node2"} <= remote_children
            # the executing nodes' dispatch attribution is present with
            # finite numbers
            dispatches = [n for n in nodes if n["name"] == "exec.dispatch"]
            assert dispatches
            for d in dispatches:
                assert math.isfinite(d["tags"]["dispatch.eval_ms"])
                assert math.isfinite(d["tags"]["dispatch.read_ms"])
            slowest_leg = max(leg["durationMs"] for leg in legs)
            stage_sum = admit_ms + slowest_leg
            err = abs(stage_sum - qms)
            rel = err / max(qms, 1e-9)
            if best is None or rel < best[0]:
                best = (rel, err, stage_sum, qms)
            if err <= max(0.10 * qms, 2.0):
                break
        rel, err, stage_sum, qms = best
        assert err <= max(0.10 * qms, 2.0), (
            f"stages {stage_sum:.2f}ms vs query_ms {qms:.2f}ms "
            f"(err {err:.2f}ms, {rel:.1%})"
        )
        # /metrics: query_ms is a real bucketed histogram with finite p99
        with urllib.request.urlopen(
            f"{c[0].node.uri}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert "# TYPE pilosa_tpu_query_ms histogram" in text
        assert lint_against_registry(text) == []
        p99 = _metrics_p99(text, "pilosa_tpu_query_ms", 'index="fr"')
        assert math.isfinite(p99) and p99 > 0.0
        # /debug/vars renders the same series with quantiles
        dbg = http_json("GET", f"{c[0].node.uri}/debug/vars")
        series = dbg["query_ms;index:fr"]
        assert math.isfinite(series["p99"]) and series["count"] >= 4
