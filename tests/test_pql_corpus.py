"""PQL grammar corpus, ported from the reference's generated-parser tests
(/root/reference/pql/pqlpeg_test.go:75-352 TestPEGWorking/TestPEGErrors).

Every input the reference's grammar accepts must parse here with the same
call count; every input it rejects must raise ParseError. This pins the
hand-rolled recursive-descent parser (pql/parser.py) to the 83-line
pql.peg grammar the generated packrat parser implements."""

import pytest

from pilosa_tpu.pql import parse
from pilosa_tpu.pql.parser import ParseError

# (input, expected call count) — TestPEGWorking corpus
VALID = [
    ("", 0),
    ("Set(2, f=10)", 1),
    ("Set('foo', f=10)", 1),
    ('Set("foo", f=10)', 1),
    ("Set(2, f=1, 1999-12-31T00:00)", 1),
    ("Set(1, a=4)Set(2, a=4)", 2),
    ("Set(1, a=4) Set(2, a=4)", 2),
    ("Set(1, a=4) \n Set(2, a=4)", 2),
    ("Set(1, a=4)Blerg(z=ha)", 2),
    ("Set(1, a=4)Blerg(z=ha)Set(2, z=99)", 3),
    ("Arb(q=1, a=4)Set(1, z=9)Arb(z=99)", 3),
    ("Set(1, a=zoom)", 1),
    ("Set(1, a=4, b=5)", 1),
    ("Set(1, a=4, bsd=haha)", 1),
    ("Set(1, a=4, 2017-04-03T19:34)", 1),
    ("Union()", 1),
    ("Union(Row(a=1))", 1),
    ("Union(Row(a=1), Row(z=44))", 1),
    ("Union(Intersect(Row(), Union(Row(), Row())), Row())", 1),
    ("TopN(boondoggle)", 1),
    ("TopN(boon, doggle=9)", 1),
    ("B(a=\"zm''e\")", 1),
    ("B(a='zm\"\"e')", 1),
    ("SetRowAttrs(blah, 9, a=47)", 1),
    ("SetRowAttrs(blah, 9, a=47, b=bval)", 1),
    ("SetRowAttrs(blah, 'rowKey', a=47)", 1),
    ('SetRowAttrs(blah, "rowKey", a=47)', 1),
    ("SetColumnAttrs(9, a=47)", 1),
    ("SetColumnAttrs(9, a=47, b=bval)", 1),
    ("SetColumnAttrs('colKey', a=47)", 1),
    ('SetColumnAttrs("colKey", a=47)', 1),
    ("Clear(1, a=53)", 1),
    ("Clear(1, a=53, b=33)", 1),
    ("TopN(myfield, n=44)", 1),
    ("TopN(myfield, Row(a=47), n=10)", 1),
    ("Row(a < 4)", 1),
    ("Row(a > 4)", 1),
    ("Row(a <= 4)", 1),
    ("Row(a >= 4)", 1),
    ("Row(a == 4)", 1),
    ("Row(a != null)", 1),
    ("Row(4 < a < 9)", 1),
    ("Row(4 < a <= 9)", 1),
    ("Row(4 <= a < 9)", 1),
    ("Row(4 <= a <= 9)", 1),
    ("Row(a=4, from=2010-07-04T00:00, to=2010-08-04T00:00)", 1),
    ("Row(a=4, from='2010-07-04T00:00', to=\"2010-08-04T00:00\")", 1),
    ("Row(a=4, from='2010-07-04T00:00')", 1),
    ('Row(a=4, to="2010-08-04T00:00")', 1),
    ("Set(1, my-frame=9)", 1),
    ("Set(\n1,\na\n=9)", 1),
    ("Range(blah=1, 2019-04-07T00:00, 2019-08-07T00:00)", 1),
    ("GroupBy(Rows(a), Rows(b), previous=[1, 2])", 1),
    ("GroupBy(Rows(a), Rows(b), previous=['k', 2], limit=10)", 1),
    ("GroupBy(Rows(a, previous=4), Rows(b, previous=7))", 1),
]

# TestPEGErrors corpus — must raise
INVALID = [
    "Set",
    "Set(1, a=4, 2017-94-03T19:34)",
    "Set(1, 2017-04-03T19:34)",
    "Set(, 1, a=4)",
    "Zeeb(, a=4)",
    "SetRowAttrs(blah, 9)",
    "Clear(9)",
    "Row(a>4, 2010-07-04T00:00, 2010-08-07T00:00)",
    "Row(a=4, 2010-07-04T00:00)",
    "Row(a=9223372036854775808)",
    "Row(a=-9223372036854775809)",
]


@pytest.mark.parametrize("src,ncalls", VALID, ids=[v[0][:40] or "empty" for v in VALID])
def test_grammar_accepts(src, ncalls):
    q = parse(src)
    assert len(q.calls) == ncalls, src


@pytest.mark.parametrize("src", INVALID, ids=[s[:40] for s in INVALID])
def test_grammar_rejects(src):
    with pytest.raises(ParseError):
        parse(src)


def test_deep_equality_set():
    """Argument mapping parity (pqlpeg_test.go TestPQLDeepEquality)."""
    (c,) = parse("Set(1, a=7, 2010-07-08T14:44)").calls
    assert c.name == "Set"
    assert c.args["a"] == 7
    assert c.args["_col"] == 1
    assert c.args["_timestamp"] == "2010-07-08T14:44"


def test_deep_equality_setrowattrs():
    (c,) = parse("SetRowAttrs(myfield, 9, z=4)").calls
    assert c.args == {"z": 4, "_field": "myfield", "_row": 9}
    (c,) = parse("SetRowAttrs(myfield, 'rowKey', z=4)").calls
    assert c.args == {"z": 4, "_field": "myfield", "_row": "rowKey"}


def test_condition_ints_also_bounded():
    with pytest.raises(ParseError):
        parse("Row(9223372036854775808 < a < 9223372036854775810)")
    with pytest.raises(ParseError):
        parse("Row(1 < a < 9223372036854775808)")
