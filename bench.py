"""Headline benchmark: BASELINE configs on a 1-billion-column index.

Reports BOTH of VERDICT round-1's requested numbers:
- device: the raw compiled kernel for Count(Intersect(Row,Row)) over the
  954-shard [S, W] stacks, batch-256 salted dispatches so the host<->TPU
  tunnel RTT (~65 ms on this dev setup) amortizes to noise; this is the
  HBM-roofline number (achieved GB/s reported in extras).
- system: the same query as a PQL string through api.query -> Executor ->
  compiled stacked plan (BASELINE config #1's query path), timed end to
  end. Each query is one device dispatch + one host read, so on tunneled
  hardware it is RTT-bound; extras report the measured RTT alongside
  (RTT jitter is of the same order as the device residue, so subtracting
  would be noise). On colocated hardware system converges to the device
  number.

Also recorded (extras): config #2 TopN(f, n=100) over all 954 shards —
r3: answered entirely from exact host metadata (rank caches + O(1) row
cardinalities), zero device dispatches — plus filtered TopN (chunked
device tally of candidate planes against the stacked filter bitmap, the
r3 device path) and config #3 BSI Sum over the full index (one stacked
dispatch, 8 bit planes).

The reference publishes no absolute numbers (BASELINE.md "published: {}"),
so vs_baseline is measured on the spot: the same popcount(a & b) with
vectorized numpy on the host CPU — the reference's execution model
(per-shard CPU bitmap math) minus its Python/HTTP overheads, i.e. a
generous stand-in for the Go engine. vs_baseline = CPU / TPU-device.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", "extras"}.
"""

import json
import os
import sys
import time

os.environ.setdefault("PILOSA_TPU_HBM_BUDGET_MB", "16384")
# bigger tally tiles at bench scale: fewer filtered-TopN chunk dispatches
os.environ.setdefault("PILOSA_TPU_GROUPBY_TILE_MB", "1024")

import numpy as np

BATCH = int(os.environ.get("PILOSA_TPU_BENCH_BATCH", "256"))
WINDOWS = 4
N_COLS = int(os.environ.get("PILOSA_TPU_BENCH_COLS", "1000000000"))
BSI_DEPTH = 8


def _median_ms(fn, reps):
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1000)
    return float(np.median(out))


def main():
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT
    from pilosa_tpu.server.node import NodeServer
    from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW

    n_shards = (N_COLS + SHARD_WIDTH - 1) // SHARD_WIDTH
    shape = (n_shards, WORDS_PER_ROW)
    rng = np.random.default_rng(7)

    # ~25% bit density: dense-ish rows (worst case for the compute path;
    # sparse shards would be skipped by the executor's shard index).
    def dense(density_and=True):
        x = rng.integers(0, 2**32, shape, np.uint32)
        return (x & rng.integers(0, 2**32, shape, np.uint32)) if density_and else x

    a_h = dense()
    b_h = dense()

    # ---- the system under test: a real node (in-memory), PQL via api ----
    srv = NodeServer(None, "bench")
    srv.start()
    try:
        api = srv.api
        api.create_index("bx")
        api.create_field("bx", "f")
        idx = srv.holder.index("bx")
        f = idx.field("f")
        for s in range(n_shards):
            f.import_row_words(1, s, a_h[s])
            f.import_row_words(2, s, b_h[s])
        # TopN corpus: 30 extra sparse rows so the rank-cache merge is real
        n_bits = 200_000
        rows = rng.integers(3, 33, n_bits).astype(np.uint64)
        cols = rng.integers(0, n_shards * SHARD_WIDTH, n_bits).astype(np.uint64)
        f.import_bits(rows, cols)
        # BSI field: 8 planes ingested word-level straight into the bsig
        # view (synthetic planes ⊆ exists; value = Σ 2^d · plane_d bits)
        api.create_field(
            "bx", "v", {"type": "int", "min": 0, "max": (1 << BSI_DEPTH) - 1}
        )
        v = idx.field("v")
        bsiv = v._view_create(v.bsi_view_name())
        exists_h = dense(density_and=False)  # ~50%
        plane_sum = 0
        for s in range(n_shards):
            bsiv.fragment(s).import_row_words(BSI_EXISTS_BIT, exists_h[s])
        for d in range(BSI_DEPTH):
            plane = (
                rng.integers(0, 2**32, shape, np.uint32) & exists_h
            ).astype(np.uint32)
            plane_sum += (1 << d) * int(
                np.bitwise_count(plane).sum()
                if hasattr(np, "bitwise_count")
                else np.unpackbits(plane.view(np.uint8)).sum()
            )
            for s in range(n_shards):
                bsiv.fragment(s).import_row_words(BSI_OFFSET_BIT + d, plane[s])

        # ---- device kernel (the r1 methodology, batch 256) ----
        a = jax.device_put(a_h)
        b = jax.device_put(b_h)

        @jax.jit
        def count_and_salted(a, b, salt):
            x = jnp.bitwise_and(jnp.bitwise_xor(a, salt), b)
            return jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)

        expect = int(count_and_salted(a, b, np.uint32(0)))  # warm + truth
        salt_i = 1
        window_ms = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            outs = []
            for _ in range(BATCH):
                outs.append(count_and_salted(a, b, np.uint32(salt_i)))
                salt_i += 1
            _ = int(outs[-1])  # host read syncs the stream
            window_ms.append((time.perf_counter() - t0) * 1000 / BATCH)
        device_ms = float(np.median(window_ms))
        bytes_per_q = 2 * n_shards * WORDS_PER_ROW * 4
        device_gbps = bytes_per_q / (device_ms / 1000) / 1e9

        # device-resident burst: BATCH salted queries inside ONE dispatch
        # (lax.fori_loop) — the per-dispatch-overhead-free HBM number
        @jax.jit
        def burst(a, b, k0):
            def body(i, acc):
                x = jnp.bitwise_and(jnp.bitwise_xor(a, i.astype(jnp.uint32)), b)
                return acc + jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)
            return jax.lax.fori_loop(k0, k0 + BATCH, body, jnp.uint32(0))

        _ = int(burst(a, b, jnp.uint32(0)))  # warm
        burst_ms = float(
            np.min(
                [
                    _median_ms(lambda: int(burst(a, b, jnp.uint32(1))), 1) / BATCH
                    for _ in range(5)
                ]
            )
        )
        burst_gbps = bytes_per_q / (burst_ms / 1000) / 1e9

        # multi-query burst: 4 salted queries per sweep — the fixed
        # per-iteration cost amortizes and per-query time ~halves (the
        # regime the executor's multi-Count batching exploits; analysis in
        # BENCH_NOTES.md)
        MQ = 4

        @jax.jit
        def burst_mq(a, b, k0):
            def body(i, acc):
                salts = k0 + i * MQ + jnp.arange(MQ, dtype=jnp.uint32)
                x = jnp.bitwise_and(
                    jnp.bitwise_xor(a[None], salts[:, None, None]), b[None]
                )
                return acc + jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)
            return jax.lax.fori_loop(
                jnp.uint32(0), jnp.uint32(BATCH // MQ), body, jnp.uint32(0)
            )

        _ = int(burst_mq(a, b, jnp.uint32(0)))  # warm
        mq_ms = float(
            np.min(
                [
                    _median_ms(lambda: int(burst_mq(a, b, jnp.uint32(1))), 1) / BATCH
                    for _ in range(5)
                ]
            )
        )
        mq_gbps_effective = bytes_per_q / (mq_ms / 1000) / 1e9

        # ---- tunnel RTT (dispatch + sync of a trivial op) ----
        tiny = jax.device_put(np.uint32(1))
        add1 = jax.jit(lambda x: x + 1)
        _ = int(add1(tiny))
        rtt_ms = _median_ms(lambda: int(add1(tiny)), 5)

        # ---- system numbers through api.query ----
        q_count = "Count(Intersect(Row(f=1), Row(f=2)))"
        got = api.query("bx", q_count)[0]  # warm: compile + stack build
        assert got == expect, (got, expect)
        system_ms = _median_ms(lambda: api.query("bx", q_count), 12)

        # multi-Count batching: 4 counts in one PQL request = ONE dispatch
        # + one host read — per-query system cost ~RTT/4
        q_multi = (
            "Count(Intersect(Row(f=1), Row(f=2)))"
            "Count(Union(Row(f=1), Row(f=2)))"
            "Count(Xor(Row(f=1), Row(f=2)))"
            "Count(Difference(Row(f=1), Row(f=2)))"
        )
        multi_got = api.query("bx", q_multi)  # warm
        assert multi_got[0] == expect, multi_got
        system_mq4_ms = _median_ms(lambda: api.query("bx", q_multi), 8) / 4

        (topn,) = api.query("bx", "TopN(f, n=100)")  # warm
        assert topn and topn[0].id in (1, 2), topn[:3]
        topn_ms = _median_ms(lambda: api.query("bx", "TopN(f, n=100)"), 5)

        q_topn_f = "TopN(f, Row(f=2), n=100)"
        (topn_f,) = api.query("bx", q_topn_f)  # warm: plane-stack build
        assert topn_f and topn_f[0].id == 2, topn_f[:3]
        topn_filtered_ms = _median_ms(lambda: api.query("bx", q_topn_f), 5)

        (sum_vc,) = api.query("bx", "Sum(field=v)")  # warm (stack build)
        assert sum_vc.value == plane_sum, (sum_vc.value, plane_sum)
        sum_ms = _median_ms(lambda: api.query("bx", "Sum(field=v)"), 5)
    finally:
        srv.stop()

    # ---- CPU comparator: vectorized numpy popcount, same data ----
    if hasattr(np, "bitwise_count"):
        def cpu_count():
            return int(np.bitwise_count(a_h & b_h).sum())
    else:
        lut = np.array([bin(i).count("1") for i in range(1 << 16)], np.uint16)
        def cpu_count():
            return int(lut[(a_h & b_h).view(np.uint16)].sum(dtype=np.int64))

    got = cpu_count()
    assert got == expect, (got, expect)
    cpu_ms = _median_ms(cpu_count, 3)

    print(
        json.dumps(
            {
                "metric": "count_intersect_1b_cols_per_query_ms",
                "value": round(device_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / device_ms, 2),
                "extras": {
                    "system_ms": round(system_ms, 3),
                    "rtt_ms": round(rtt_ms, 3),
                    "device_gbps": round(device_gbps, 1),
                    "device_burst_ms": round(burst_ms, 4),
                    "device_burst_gbps": round(burst_gbps, 1),
                    "device_mq4_ms": round(mq_ms, 4),
                    "device_mq4_gbps_effective": round(mq_gbps_effective, 1),
                    "system_mq4_ms": round(system_mq4_ms, 3),
                    "cpu_baseline_ms": round(cpu_ms, 3),
                    "topn_n100_954shards_ms": round(topn_ms, 3),
                    "topn_filtered_n100_ms": round(topn_filtered_ms, 3),
                    "bsi_sum_1b_cols_ms": round(sum_ms, 3),
                    "batch": BATCH,
                    "n_shards": n_shards,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
