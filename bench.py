"""Headline benchmark: Count(Intersect(Row, Row)) on a 1-billion-column index.

BASELINE.md north star: Count(Intersect) at 10B cols x 1M rows < 10 ms p50 on
a v5e-64. This single-chip bench runs the same query shape at 1B columns
(954 shards x 2^20 cols) — the per-chip slice of the 64-chip target — as one
fused device reduction (no CPU bitmap math on the query path).

The reference publishes no absolute numbers (BASELINE.md: "published: {}"),
so vs_baseline is measured on the spot: the same popcount(a & b) computed
with vectorized numpy (16-bit LUT) on the host CPU — the reference's
execution model (per-shard CPU bitmap math) with Python/HTTP overheads
removed, i.e. a generous stand-in for the Go engine. vs_baseline = CPU p50 /
TPU p50 (higher = faster than baseline).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    from pilosa_tpu.parallel.mesh import count_and_stacked
    from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW

    n_cols = 1_000_000_000
    n_shards = (n_cols + SHARD_WIDTH - 1) // SHARD_WIDTH
    shape = (n_shards, WORDS_PER_ROW)

    rng = np.random.default_rng(7)
    # ~25% bit density: dense-ish rows (worst case for the compute path;
    # sparse shards would be skipped by the executor's shard index).
    a_h = (rng.integers(0, 2**32, shape, np.uint32) & rng.integers(0, 2**32, shape, np.uint32)).astype(np.uint32)
    b_h = (rng.integers(0, 2**32, shape, np.uint32) & rng.integers(0, 2**32, shape, np.uint32)).astype(np.uint32)

    a = jax.device_put(a_h)
    b = jax.device_put(b_h)
    # warmup / compile
    expect = int(count_and_stacked(a, b))

    iters = 30
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = count_and_stacked(a, b)
        out.block_until_ready()
        times.append((time.perf_counter() - t0) * 1000)
    tpu_p50 = float(np.median(times))

    # CPU comparator: vectorized numpy popcount over the same data.
    if hasattr(np, "bitwise_count"):
        def cpu_count():
            return int(np.bitwise_count(a_h & b_h).sum())
    else:
        lut = np.array([bin(i).count("1") for i in range(1 << 16)], np.uint16)
        def cpu_count():
            return int(lut[(a_h & b_h).view(np.uint16)].sum(dtype=np.int64))

    cpu_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        got = cpu_count()
        cpu_times.append((time.perf_counter() - t0) * 1000)
    cpu_p50 = float(np.median(cpu_times))
    assert got == expect, (got, expect)

    print(
        json.dumps(
            {
                "metric": "count_intersect_1b_cols_p50_ms",
                "value": round(tpu_p50, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_p50 / tpu_p50, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
