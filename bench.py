"""Headline benchmark: Count(Intersect(Row, Row)) on a 1-billion-column index.

BASELINE.md north star: Count(Intersect) at 10B cols x 1M rows < 10 ms p50 on
a v5e-64. This single-chip bench runs the same query shape at 1B columns
(954 shards x 2^20 cols) — the per-chip slice of the 64-chip target — as one
fused device reduction (no CPU bitmap math on the query path).

Measurement notes:
- Each timed iteration XORs a fresh per-iteration salt into one operand, so
  no dispatch/result cache (XLA or the hosted-TPU tunnel) can satisfy a
  repeat execution without recomputing.
- A batch of BATCH salted queries is dispatched per timed window and synced
  once with a host read; per-query latency = window / BATCH. This amortizes
  host<->device round-trip latency (the tunneled single-chip dev setup has
  ~65 ms RTT that would otherwise swamp sub-ms device compute, and a real
  deployment pipelines queries the same way).

The reference publishes no absolute numbers (BASELINE.md: "published: {}"),
so vs_baseline is measured on the spot: the same popcount(a & b) computed
with vectorized numpy (16-bit LUT / AVX bitwise_count) on the host CPU — the
reference's execution model (per-shard CPU bitmap math) with Python/HTTP
overheads removed, i.e. a generous stand-in for the Go engine. vs_baseline =
CPU per-query / TPU per-query (higher = faster than baseline).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BATCH = 16
WINDOWS = 8


def main():
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW

    n_cols = 1_000_000_000
    n_shards = (n_cols + SHARD_WIDTH - 1) // SHARD_WIDTH
    shape = (n_shards, WORDS_PER_ROW)

    rng = np.random.default_rng(7)
    # ~25% bit density: dense-ish rows (worst case for the compute path;
    # sparse shards would be skipped by the executor's shard index).
    a_h = (rng.integers(0, 2**32, shape, np.uint32) & rng.integers(0, 2**32, shape, np.uint32)).astype(np.uint32)
    b_h = (rng.integers(0, 2**32, shape, np.uint32) & rng.integers(0, 2**32, shape, np.uint32)).astype(np.uint32)

    a = jax.device_put(a_h)
    b = jax.device_put(b_h)

    @jax.jit
    def count_and_salted(a, b, salt):
        x = jnp.bitwise_and(jnp.bitwise_xor(a, salt), b)
        return jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)

    # warmup / compile; salt=0 gives the unsalted ground truth
    expect = int(count_and_salted(a, b, np.uint32(0)))

    salt_i = 1
    window_ms = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        acc = 0
        outs = []
        for _ in range(BATCH):
            outs.append(count_and_salted(a, b, np.uint32(salt_i)))
            salt_i += 1
        acc = int(outs[-1])  # host read syncs the stream
        t1 = time.perf_counter()
        assert acc > 0
        window_ms.append((t1 - t0) * 1000 / BATCH)
    tpu_q = float(np.median(window_ms))

    # CPU comparator: vectorized numpy popcount over the same data.
    if hasattr(np, "bitwise_count"):
        def cpu_count():
            return int(np.bitwise_count(a_h & b_h).sum())
    else:
        lut = np.array([bin(i).count("1") for i in range(1 << 16)], np.uint16)
        def cpu_count():
            return int(lut[(a_h & b_h).view(np.uint16)].sum(dtype=np.int64))

    cpu_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        got = cpu_count()
        cpu_times.append((time.perf_counter() - t0) * 1000)
    cpu_q = float(np.median(cpu_times))
    assert got == expect, (got, expect)

    print(
        json.dumps(
            {
                "metric": "count_intersect_1b_cols_per_query_ms",
                "value": round(tpu_q, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_q / tpu_q, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
