"""Headline benchmark: BASELINE configs on a 1-billion-column index.

Reports BOTH of VERDICT round-1's requested numbers:
- device: the raw compiled kernel for Count(Intersect(Row,Row)) over the
  954-shard [S, W] stacks, batch-256 salted dispatches so the host<->TPU
  tunnel RTT (~65-100 ms on this dev setup) amortizes to noise; this is
  the HBM-roofline number (achieved GB/s reported in extras).
- system: the same query as a PQL string through api.query -> Executor ->
  compiled stacked plan (BASELINE config #1's query path), timed end to
  end. Each query is one device dispatch + one host read, so on tunneled
  hardware it is RTT-bound; extras report the measured RTT alongside.
  On colocated hardware system converges to the device number. The
  cross-request amortization story is system_concurrent8_ms: 8 client
  threads sharing dispatches through the group-commit batcher
  (exec/batcher.py) — per-query latency approaches RTT/8 + device.

Also recorded (extras):
- config #2: TopN(f, n=100) over all 954 shards (zero-dispatch host
  metadata path) and filtered TopN (r5: ONE device read per query —
  one-pass select + sparse gather tally, exec/executor.py).
- config #3: BSI Sum over the full index (one stacked dispatch).
- config #4: GroupBy over 3 fields x 64 shards (192 groups), system ms.
- config #5: mesh_scaling — Count/Union/Xor multi-query dispatch on a
  virtual 1/2/4/8-device CPU mesh (the same NamedSharding program the
  multichip dryrun compiles; a trend stand-in until real multi-chip).
- hbm_evict_count_ms: the count query with the HBM budget forced below
  the working set — the eviction path must stay correct and the cliff is
  recorded (VERDICT r4 weak #5).

The reference publishes no absolute numbers (BASELINE.md "published: {}"),
so vs_baseline is measured on the spot: the same popcount(a & b) with
vectorized numpy on the host CPU — the reference's execution model
(per-shard CPU bitmap math) minus its Python/HTTP overheads, i.e. a
generous stand-in for the Go engine. vs_baseline = CPU / TPU-device.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", "extras"}.
"""

import json
import os
import subprocess
import sys
import threading
import time

BATCH = int(os.environ.get("PILOSA_TPU_BENCH_BATCH", "256"))
WINDOWS = 4
N_COLS = int(os.environ.get("PILOSA_TPU_BENCH_COLS", "1000000000"))
BSI_DEPTH = 8
GB_SHARDS = 64  # config 4 geometry
MIXED_SECONDS = float(os.environ.get("PILOSA_TPU_BENCH_MIXED_S", "3.0"))
MIXED_SHARDS = 64  # sustained mixed read/write geometry
TQ_SHARDS = 8  # time-quantum range-query geometry


def _median_ms(fn, reps):
    import numpy as np

    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1000)
    return float(np.median(out))


def mesh_scaling_main():
    """Config 5 stand-in (runs in a CPU subprocess): the multi-Count
    stacked-plan dispatch on a virtual 1/2/4/8-device mesh. Prints one
    JSON list of {devices, mq4_ms} rows."""
    from pilosa_tpu.utils.cpuonly import force_cpu

    force_cpu(8)

    import jax
    import numpy as np

    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import mesh as pmesh
    from pilosa_tpu.shardwidth import WORDS_PER_ROW

    from pilosa_tpu.core.resultcache import RESULT_CACHE

    # scaling numbers measure the compiled dispatch, not the result
    # cache's revalidation fast path (which would serve every repeat)
    RESULT_CACHE.configure(budget_bytes=0)

    n_shards = 64
    rng = np.random.default_rng(3)
    h = Holder().open()
    idx = h.create_index("ms")
    f = idx.create_field("f", FieldOptions())
    for s in range(n_shards):
        f.import_row_words(1, s, rng.integers(0, 2**32, WORDS_PER_ROW, np.uint32))
        f.import_row_words(2, s, rng.integers(0, 2**32, WORDS_PER_ROW, np.uint32))
    ex = Executor(h)
    q = (
        "Count(Intersect(Row(f=1), Row(f=2)))"
        "Count(Union(Row(f=1), Row(f=2)))"
        "Count(Xor(Row(f=1), Row(f=2)))"
        "Count(Difference(Row(f=1), Row(f=2)))"
    )
    rows = []
    truth = None
    for n in (1, 2, 4, 8):
        # pure shard-axis mesh: config 5 is about scaling the shard
        # (data-parallel) dimension; the default 2D factoring puts a
        # cols split at n=4 that adds collective overhead without adding
        # shard parallelism (the multichip dryrun certifies the 2D mesh)
        pmesh.set_active_mesh(
            pmesh.make_mesh(jax.devices()[:n], shards_axis=n) if n > 1 else None
        )
        DEVICE_CACHE.clear()  # rebuild stacks under the new sharding
        got = ex.execute("ms", q)  # warm: compile + stack build
        if truth is None:
            truth = got
        assert got == truth, (n, got, truth)
        # min-of-medians: the shared host's CPU load swings individual
        # medians by 2x; the min is the contention-free estimate
        ms = min(_median_ms(lambda: ex.execute("ms", q), 7) for _ in range(3))
        rows.append({"devices": n, "mq4_ms": round(ms, 3)})
    base = rows[0]["mq4_ms"]
    for r in rows:
        r["speedup"] = round(base / r["mq4_ms"], 2)
    print(json.dumps(rows))


def replicated_bench(seconds=None, writers=8, sync_interval=0.0):
    """Replicated mixed read/write — the benched configuration (ISSUE 12):
    two NodeServers with REAL data dirs (WAL + fsync on the bench host's
    filesystem) and real HTTP between them, replica_n=2, `writers`
    concurrent import threads driving api.import_bits under the strict
    group-commit WAL while a Count stream runs against the same node.
    Reports aggregate logical ingest bits/s (each bit also lands on the
    replica — physical write volume is 2x), the fsyncs-per-import
    coalescing ratio and mean commit-group size from the group-commit
    counters, and query p99 under replicated ingest from the PR 6
    flight-recorder histograms."""
    import shutil
    import tempfile

    import numpy as np

    from pilosa_tpu.core import wal as walmod
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.testing import ClusterHarness

    if seconds is None:
        seconds = float(os.environ.get("PILOSA_TPU_BENCH_REPL_S", "3.0"))
    n_shards = 16
    base = tempfile.mkdtemp(prefix="pilosa-benchrepl-")
    try:
        with ClusterHarness(
            2, replica_n=2, base_dir=base, wal_sync_interval=sync_interval
        ) as c:
            api = c[0].api
            api.create_index("rx")
            api.create_field("rx", "f", {"type": "set"})
            rng = np.random.default_rng(5)
            cols0 = rng.integers(0, n_shards * SHARD_WIDTH, 20_000).astype(
                np.uint64
            )
            api.import_bits("rx", "f", np.ones(len(cols0), np.uint64), cols0)
            api.query("rx", "Count(Row(f=1))")  # warm: stage + compile
            # drop warm-up observations: the histogram must hold ONLY
            # queries issued under replicated ingest pressure
            c[0].stats.registry.drop_label("index", "rx")
            w0 = walmod.stats_snapshot()
            stop = threading.Event()
            wrote = [0] * writers
            calls = [0] * writers
            errs = []

            def writer(t):
                try:
                    wrng = np.random.default_rng(200 + t)
                    batch = 20_000
                    while not stop.is_set():
                        r = wrng.integers(1, 9, batch).astype(np.uint64)
                        cl = wrng.integers(
                            0, n_shards * SHARD_WIDTH, batch
                        ).astype(np.uint64)
                        api.import_bits("rx", "f", r, cl)
                        wrote[t] += batch
                        calls[t] += 1
                except BaseException as e:  # noqa: BLE001 - fail the bench
                    errs.append(e)

            threads = [
                threading.Thread(target=writer, args=(t,))
                for t in range(writers)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            queries = 0
            try:
                while time.perf_counter() - t0 < seconds:
                    api.query("rx", "Count(Row(f=1))")
                    queries += 1
            finally:
                stop.set()
                for t in threads:
                    t.join()
            elapsed = time.perf_counter() - t0
            if errs:  # a dead writer fakes the numbers
                raise errs[0]
            w1 = walmod.stats_snapshot()
            reg = c[0].stats.registry
            n_calls = sum(calls) or 1
            groups = max(w1["commit_groups"] - w0["commit_groups"], 1)
            return {
                "ingest_replicated_bits_mps": round(
                    sum(wrote) / elapsed / 1e6, 2
                ),
                "query_p99_under_replicated_ingest_ms": round(
                    reg.quantile("query_ms", 0.99, tags=("index:rx",)), 3
                ),
                "replicated_queries": queries,
                "replicated_imports": n_calls,
                "wal_fsyncs_per_import": round(
                    (w1["fsyncs"] - w0["fsyncs"]) / n_calls, 3
                ),
                # per WAL APPEND (one per fragment touched per node):
                # the group commit's real coalescing ratio when a call
                # fans across many fragment files
                "wal_fsyncs_per_append": round(
                    (w1["fsyncs"] - w0["fsyncs"])
                    / max(w1["commits"] - w0["commits"], 1),
                    3,
                ),
                "wal_commit_group_mean": round(
                    (w1["commits"] - w0["commits"]) / groups, 2
                ),
            }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def tier_bench():
    """Tiered-storage families (ISSUE 18): demote throughput, cold
    first-query hydration latency, and a beyond-RAM run — corpus bigger
    than the configured host budget, hot working set served from local
    fragments at unchanged latency while the cold rest lives in the
    store. The store is a LocalDirStore behind a SlowStoreWrapper (5 ms
    per op), modeling a same-region object store's round trip rather
    than pretending local-disk numbers are remote numbers."""
    import shutil
    import tempfile

    import numpy as np

    from pilosa_tpu.core.field import FieldOptions
    from pilosa_tpu.core.holder import Holder
    from pilosa_tpu.shardwidth import WORDS_PER_ROW
    from pilosa_tpu.tier import TierManager, TierPolicy
    from pilosa_tpu.tier.store import LocalDirStore, SlowStoreWrapper

    n_shards = 16
    n_rows = 4
    hot = list(range(4))  # the working set the budget must keep local
    rng = np.random.default_rng(21)
    base = tempfile.mkdtemp(prefix="pilosa-benchtier-")
    try:
        h = Holder(os.path.join(base, "data")).open()
        idx = h.create_index_if_not_exists("tb")
        f = idx.create_field_if_not_exists("f", FieldOptions())
        for s in range(n_shards):
            for row in range(n_rows):
                f.import_row_words(
                    row, s,
                    rng.integers(0, 2**32, WORDS_PER_ROW, dtype=np.uint32),
                )
        v = f.views["standard"]
        for fr in v.fragments.values():
            fr.snapshot()
        store = SlowStoreWrapper(
            LocalDirStore(os.path.join(base, "store")), 0.005
        )
        tier = TierManager(store, TierPolicy("cold"), h,
                           fetch_concurrency=4)

        def hot_read():
            for s in hot:
                v.fragments[s].row_positions(1)

        hot_ms_baseline = _median_ms(hot_read, 5)

        # demote throughput: serialize + upload (2 slow puts each) +
        # capture-drain check + local delete, all 16 fragments
        frags = [v.fragments[s] for s in sorted(v.fragments)]
        t0 = time.perf_counter()
        for fr in frags:
            assert tier.demote_fragment(v, fr)
        demote_s = time.perf_counter() - t0
        demote_bytes = tier.counters()["demote_bytes"]
        local_total = demote_bytes  # snapshots mirror local bytes here

        # cold first-query latency: each shard's FIRST read pays one
        # verified store fetch + adopt (single-flight); median per shard
        lat = []
        for s in range(n_shards):
            t0 = time.perf_counter()
            tier.hydrate(v, s)
            lat.append((time.perf_counter() - t0) * 1000)
        lat.sort()

        # beyond-RAM: budget ~1/3 of the corpus; the hot subset is
        # touched last so LRU budget pressure demotes the cold rest
        for s in range(n_shards):
            if s not in hot:
                tier.touch_many(v, (s,))
        tier.touch_many(v, hot)
        tier.host_budget_bytes = local_total // 3
        cold_n = tier.demote_tick()
        assert cold_n >= n_shards // 2, cold_n  # corpus really > budget
        for s in hot:
            assert s in v.fragments, s  # working set stayed local
        hot_ms_under_budget = _median_ms(hot_read, 5)
        h.close()
        return {
            "tier_demote_mbps": round(demote_bytes / demote_s / 1e6, 1),
            "tier_hydrate_cold_query_ms": round(lat[len(lat) // 2], 3),
            "tier_hydrate_cold_query_p95_ms": round(
                lat[int(len(lat) * 0.95)], 3
            ),
            "tier_corpus_bytes": int(local_total),
            "tier_beyond_budget_cold_fragments": int(cold_n),
            "tier_hot_query_ms_baseline": round(hot_ms_baseline, 3),
            "tier_hot_query_ms_under_budget": round(hot_ms_under_budget, 3),
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def coherence_bench():
    """Coherence-plane families (ISSUE 19): the leased fan-out warm hit
    against the wire-revalidate baseline (version-RTT counter deltas
    reported for both — the leased number is asserted ZERO), the
    write-to-delivery latency of subscription pushes, and the in-place
    monotone tree repair of a cached Intersect — each result asserted
    equal to a from-scratch recompute."""
    import numpy as np

    from pilosa_tpu.core.resultcache import RESULT_CACHE
    from pilosa_tpu.exec import plan as planmod_x
    from pilosa_tpu.server import wire
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.testing import ClusterHarness

    n_shards = 8
    reps = 30
    q = "Count(Row(f=1))"

    def seed(api):
        api.create_index("cx")
        api.create_field("cx", "f", {"type": "set"})
        rng = np.random.default_rng(17)
        for r in (1, 2):
            cols = rng.integers(0, n_shards * SHARD_WIDTH, 50_000).astype(
                np.uint64
            )
            api.import_bits(
                "cx", "f", np.full(len(cols), r, np.uint64), cols
            )

    out = {}
    # revalidate baseline: leases off, every warm fan-out hit pays the
    # /internal/versions round (one wire revalidation per hit)
    RESULT_CACHE.reset()
    with ClusterHarness(
        2, in_memory=True, telemetry_sample_interval=0.0,
        max_writes_per_request=0,
    ) as c:
        api = c[0].api
        seed(api)
        for _ in range(3):  # past the candidate gate: stored + hit
            base = api.query("cx", q)[0]
        rv0 = RESULT_CACHE.stats_snapshot()["revalidations"]
        out["fanout_warm_hit_revalidate_ms"] = round(
            _median_ms(lambda: api.query("cx", q), reps), 3
        )
        out["fanout_revalidate_wire_rounds"] = (
            RESULT_CACHE.stats_snapshot()["revalidations"] - rv0
        )

    # leased: the mirror assembles the version vector host-side
    RESULT_CACHE.reset()
    with ClusterHarness(
        2,
        in_memory=True,
        telemetry_sample_interval=0.0,
        coherence_lease_duration=30.0,
        coherence_publish_batch_ms=5.0,
        coherence_sub_poll_interval=0.2,
        max_writes_per_request=0,
    ) as c:
        api = c[0].api
        seed(api)
        got = api.query("cx", q)[0]
        assert got == base, (got, base)
        api.query("cx", q)  # mirror armed
        mgr = c[0].coherence
        rtt0 = mgr.counters_snapshot()["version_rtts"]
        out["fanout_warm_hit_leased_ms"] = round(
            _median_ms(lambda: api.query("cx", q), reps), 3
        )
        snap = mgr.counters_snapshot()
        assert snap["version_rtts"] == rtt0, "leased warm hit paid an RTT"
        out["fanout_leased_version_rtts"] = snap["version_rtts"] - rtt0
        assert snap["lease_hits"] > 0

        # subscription push: a remote-node write to a fresh column of a
        # dedicated row; latency is write-issue -> long-poll delivery,
        # every pushed result checked against the wire recompute
        qs = "Count(Row(f=3))"
        sub = api.subscribe("cx", qs)
        seq = sub["seq"]
        lat = []
        for i in range(20):
            t0 = time.perf_counter()
            c[1].api.import_bits(
                "cx", "f",
                np.array([3], np.uint64), np.array([i], np.uint64),
            )
            snap_s = mgr.poll(sub["id"], after=seq, wait_s=30.0)
            lat.append((time.perf_counter() - t0) * 1000)
            assert snap_s is not None and snap_s["seq"] > seq, snap_s
            seq = snap_s["seq"]
            want = [
                wire.result_to_public_json(r)
                for r in api.query_response("cx", qs).results
            ]
            assert snap_s["result"] == want, (snap_s["result"], want)
        lat.sort()
        out["subscription_push_p50_ms"] = round(lat[len(lat) // 2], 3)
        out["subscription_push_p95_ms"] = round(
            lat[int(len(lat) * 0.95)], 3
        )

    # monotone tree repair: set-only bursts into a cached Intersect are
    # patched host-side from the merge barrier's word deltas — zero
    # compiled dispatches, asserted equal to a cache-dropped recompute
    RESULT_CACHE.reset()
    with ClusterHarness(
        1, in_memory=True, telemetry_sample_interval=0.0,
        max_writes_per_request=0,
    ) as c:
        api = c[0].api
        api.create_index("rx")
        api.create_field("rx", "f", {"type": "set"})
        for r, step in ((1, 2), (2, 3)):
            cols = np.arange(0, 300_000, step, dtype=np.uint64)
            api.import_bits(
                "rx", "f", np.full(len(cols), r, np.uint64), cols
            )
        qr = "Count(Intersect(Row(f=1), Row(f=2)))"
        api.query("rx", qr)
        api.query("rx", qr)  # stored
        # keep the bursts STAGED: the op-count snapshot trigger would
        # merge them inside the import call, leaving the read barrier
        # nothing to repair from (same idiom as the merge rooflines)
        fobj = c[0].holder.index("rx").field("f")
        for fr in fobj.view("standard").fragments.values():
            fr.max_op_n = max(fr.max_op_n, 1 << 22)
        tr0 = RESULT_CACHE.stats_snapshot()["tree_repairs"]
        ev0 = planmod_x.STATS["evals"]
        lat = []
        got = None
        for i in range(10):
            cols = np.arange(
                500_000 + i * 2_000, 500_000 + (i + 1) * 2_000,
                dtype=np.uint64,
            )
            api.import_bits(
                "rx", "f", np.full(len(cols), 1, np.uint64), cols
            )
            t0 = time.perf_counter()
            got = api.query("rx", qr)[0]
            lat.append((time.perf_counter() - t0) * 1000)
        assert RESULT_CACHE.stats_snapshot()["tree_repairs"] >= tr0 + 10
        assert planmod_x.STATS["evals"] == ev0, "tree repair dispatched"
        RESULT_CACHE.reset()
        fresh = api.query("rx", qr)[0]
        assert got == fresh, (got, fresh)
        lat.sort()
        out["monotone_repair_ms"] = round(lat[len(lat) // 2], 3)
    return out


def main():
    os.environ.setdefault("PILOSA_TPU_HBM_BUDGET_MB", "16384")
    # bigger tally tiles at bench scale: fewer filtered-TopN chunk dispatches
    os.environ.setdefault("PILOSA_TPU_GROUPBY_TILE_MB", "1024")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pilosa_tpu.core.devcache import DEVICE_CACHE
    from pilosa_tpu.core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT
    from pilosa_tpu.server.node import NodeServer
    from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_ROW

    n_shards = (N_COLS + SHARD_WIDTH - 1) // SHARD_WIDTH
    shape = (n_shards, WORDS_PER_ROW)
    rng = np.random.default_rng(7)

    # ~25% bit density: dense-ish rows (worst case for the compute path;
    # sparse shards would be skipped by the executor's shard index).
    def dense(density_and=True):
        x = rng.integers(0, 2**32, shape, np.uint32)
        return (x & rng.integers(0, 2**32, shape, np.uint32)) if density_and else x

    a_h = dense()
    b_h = dense()

    # ---- the system under test: a real node (in-memory), PQL via api ----
    # cache_result_mb=0: every repeated-query median below measures the
    # EXECUTION cost (dispatches, staging, reads); the result cache gets
    # its own section, which enables it explicitly and measures the
    # revalidation/repair fast path against these numbers
    srv = NodeServer(None, "bench", cache_result_mb=0)
    srv.start()
    try:
        api = srv.api
        api.create_index("bx")
        api.create_field("bx", "f")
        idx = srv.holder.index("bx")
        f = idx.field("f")
        for s in range(n_shards):
            f.import_row_words(1, s, a_h[s])
            f.import_row_words(2, s, b_h[s])
        # TopN corpus: 30 extra sparse rows so the rank-cache merge is real
        # (timed: this is the position-wise ingest path, the analog of the
        # reference's fragment import benchmarks, fragment_internal_test.go)
        n_bits = 200_000
        rows = rng.integers(3, 33, n_bits).astype(np.uint64)
        cols = rng.integers(0, n_shards * SHARD_WIDTH, n_bits).astype(np.uint64)
        t0 = time.perf_counter()
        f.import_bits(rows, cols)
        ingest_bits_mps = n_bits / (time.perf_counter() - t0) / 1e6
        # steady-state rate: the first call pays fragment creation; the
        # staged fast path's sustained number is what mixed-load serving
        # sees (both are reported)
        rows2 = rng.integers(3, 33, n_bits).astype(np.uint64)
        cols2 = rng.integers(0, n_shards * SHARD_WIDTH, n_bits).astype(np.uint64)
        t0 = time.perf_counter()
        f.import_bits(rows2, cols2)
        ingest_bits_mps_warm = n_bits / (time.perf_counter() - t0) / 1e6
        # BSI field: 8 planes ingested word-level straight into the bsig
        # view (synthetic planes ⊆ exists; value = Σ 2^d · plane_d bits)
        api.create_field(
            "bx", "v", {"type": "int", "min": 0, "max": (1 << BSI_DEPTH) - 1}
        )
        v = idx.field("v")
        bsiv = v._view_create(v.bsi_view_name())
        exists_h = dense(density_and=False)  # ~50%
        plane_sum = 0
        for s in range(n_shards):
            bsiv.fragment(s).import_row_words(BSI_EXISTS_BIT, exists_h[s])
        # the word-level (roaring-analog) ingest path, timed: dense rows
        # union straight into the store with no position parsing — the
        # MB/s here is the zero-parse bulk-load roofline
        planes_h = []
        for d in range(BSI_DEPTH):
            plane = (
                rng.integers(0, 2**32, shape, np.uint32) & exists_h
            ).astype(np.uint32)
            plane_sum += (1 << d) * int(
                np.bitwise_count(plane).sum()
                if hasattr(np, "bitwise_count")
                else np.unpackbits(plane.view(np.uint8)).sum()
            )
            planes_h.append(plane)
        t0 = time.perf_counter()
        for d, plane in enumerate(planes_h):
            for s in range(n_shards):
                bsiv.fragment(s).import_row_words(BSI_OFFSET_BIT + d, plane[s])
        ingest_roaring_mbps = (
            BSI_DEPTH * n_shards * WORDS_PER_ROW * 4
            / (time.perf_counter() - t0)
            / 1e6
        )
        # config 4 corpus: 3 fields over 64 shards (8 x 6 x 4 = 192 groups)
        api.create_index("gbx")
        gb_shape = (GB_SHARDS, WORDS_PER_ROW)
        gidx = srv.holder.index("gbx")
        for fname, nrows in (("ga", 8), ("gb", 6), ("gc", 4)):
            api.create_field("gbx", fname)
            gf = gidx.field(fname)
            for r in range(nrows):
                words = (
                    rng.integers(0, 2**32, gb_shape, np.uint32)
                    & rng.integers(0, 2**32, gb_shape, np.uint32)
                )
                for s in range(GB_SHARDS):
                    gf.import_row_words(r, s, words[s])

        # ---- device kernel (the r1 methodology, batch 256) ----
        a = jax.device_put(a_h)
        b = jax.device_put(b_h)

        @jax.jit
        def count_and_salted(a, b, salt):
            x = jnp.bitwise_and(jnp.bitwise_xor(a, salt), b)
            return jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)

        expect = int(count_and_salted(a, b, np.uint32(0)))  # warm + truth
        salt_i = 1
        window_ms = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            outs = []
            for _ in range(BATCH):
                outs.append(count_and_salted(a, b, np.uint32(salt_i)))
                salt_i += 1
            _ = int(outs[-1])  # host read syncs the stream
            window_ms.append((time.perf_counter() - t0) * 1000 / BATCH)
        device_ms = float(np.median(window_ms))
        bytes_per_q = 2 * n_shards * WORDS_PER_ROW * 4
        device_gbps = bytes_per_q / (device_ms / 1000) / 1e9

        # device-resident burst: BATCH salted queries inside ONE dispatch
        # (lax.fori_loop) — the per-dispatch-overhead-free HBM number
        @jax.jit
        def burst(a, b, k0):
            def body(i, acc):
                x = jnp.bitwise_and(jnp.bitwise_xor(a, i.astype(jnp.uint32)), b)
                return acc + jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)
            return jax.lax.fori_loop(k0, k0 + BATCH, body, jnp.uint32(0))

        _ = int(burst(a, b, jnp.uint32(0)))  # warm
        burst_ms = float(
            np.min(
                [
                    _median_ms(lambda: int(burst(a, b, jnp.uint32(1))), 1) / BATCH
                    for _ in range(5)
                ]
            )
        )
        burst_gbps = bytes_per_q / (burst_ms / 1000) / 1e9

        # multi-query burst: 4 salted queries per sweep — the fixed
        # per-iteration cost amortizes and per-query time ~halves (the
        # regime the executor's multi-Count batching exploits; analysis in
        # BENCH_NOTES.md)
        MQ = 4

        @jax.jit
        def burst_mq(a, b, k0):
            def body(i, acc):
                salts = k0 + i * MQ + jnp.arange(MQ, dtype=jnp.uint32)
                x = jnp.bitwise_and(
                    jnp.bitwise_xor(a[None], salts[:, None, None]), b[None]
                )
                return acc + jnp.sum(jax.lax.population_count(x), dtype=jnp.uint32)
            return jax.lax.fori_loop(
                jnp.uint32(0), jnp.uint32(BATCH // MQ), body, jnp.uint32(0)
            )

        _ = int(burst_mq(a, b, jnp.uint32(0)))  # warm
        mq_ms = float(
            np.min(
                [
                    _median_ms(lambda: int(burst_mq(a, b, jnp.uint32(1))), 1) / BATCH
                    for _ in range(5)
                ]
            )
        )
        mq_gbps_effective = bytes_per_q / (mq_ms / 1000) / 1e9

        # ---- filtered-TopN device work, RTT-amortized ----
        # The exact shapes the one-pass tally dispatches at bench scale:
        # dense-candidate cross tally [1,S,W]x[2,S,W], sparse gather of
        # ~200k live words + sorted-segment cumsum, fused [32,S] concat.
        # Batched back-to-back with ONE final sync, same methodology as
        # the count device number — this is the colocated-hardware cost
        # of a filtered TopN query (the system number is RTT-bound).
        from pilosa_tpu.exec import groupby as gbm
        from pilosa_tpu.ops import bitmap as obm

        planes2 = jax.device_put(np.stack([a_h, b_h]))  # dense candidates
        k_ent = 1 << 18
        g_idx = jax.device_put(
            rng.integers(0, n_shards * WORDS_PER_ROW, k_ent).astype(np.int32)
        )
        g_mask = jax.device_put(rng.integers(0, 2**32, k_ent, np.uint32))
        segs = np.sort(rng.integers(0, k_ent, 32 * n_shards)).astype(np.int32)
        g_starts = jax.device_put(segs)
        g_ends = jax.device_put(np.minimum(segs + 8, k_ent).astype(np.int32))

        @jax.jit
        def topn_tally_once(b, planes2, g_idx, g_mask, g_starts, g_ends, salt):
            # operands as arguments, not closure: closed-over device
            # arrays would embed as compile-time constants
            src = jnp.bitwise_xor(b, salt)
            dense_c = gbm._counts_cross(src[None], planes2)[0]
            sparse_c = obm.gather_tally_sorted(
                src, g_idx, g_mask, g_starts, g_ends
            ).reshape(32, n_shards)
            return jnp.concatenate([dense_c, sparse_c], axis=0)

        args_t = (b, planes2, g_idx, g_mask, g_starts, g_ends)
        _ = np.asarray(topn_tally_once(*args_t, np.uint32(0)))  # warm
        TB = 32
        t0 = time.perf_counter()
        outs = [topn_tally_once(*args_t, np.uint32(i + 1)) for i in range(TB)]
        _ = np.asarray(outs[-1])  # one sync for the whole batch
        topn_filtered_device_ms = (time.perf_counter() - t0) * 1000 / TB

        # ---- BSI Sum device work, RTT-amortized (config 3) ----
        # The exact shape Sum dispatches at bench scale: per-plane
        # popcounts of planes[D,S,W] & exists[S,W] in one fused [D]
        # reduction (the executor's fused aggregate read; the 2^d
        # weighting is an exact host combine). Salted back-to-back with
        # ONE final sync — without this the config-3 number sits on the
        # tunnel RTT floor (VERDICT weak #2).
        planes_dev = jax.device_put(
            np.stack(
                [
                    (
                        rng.integers(0, 2**32, shape, np.uint32) & exists_h
                    ).astype(np.uint32)
                    for _ in range(BSI_DEPTH)
                ]
            )
        )
        exists_dev = jax.device_put(exists_h)

        @jax.jit
        def bsi_sum_once(exists, planes, salt):
            src = jnp.bitwise_xor(exists, salt)
            return jnp.sum(
                jax.lax.population_count(jnp.bitwise_and(planes, src[None])),
                axis=(1, 2),
                dtype=jnp.uint32,
            )

        _ = np.asarray(bsi_sum_once(exists_dev, planes_dev, np.uint32(0)))
        t0 = time.perf_counter()
        outs = [
            bsi_sum_once(exists_dev, planes_dev, np.uint32(i + 1))
            for i in range(TB)
        ]
        _ = np.asarray(outs[-1])  # one sync for the whole batch
        bsi_sum_device_ms = (time.perf_counter() - t0) * 1000 / TB
        del planes_dev, exists_dev

        # ---- GroupBy device work, RTT-amortized (config 4) ----
        # The tally kernel at config-4 geometry: ga's 8 rows crossed with
        # the 24 (gb x gc) pair rows over 64 shards -> [8, 24, S] counts,
        # the same _counts_cross the executor's group_by_device runs.
        from pilosa_tpu.exec import groupby as gbm_dev

        gb_shape3 = (GB_SHARDS, WORDS_PER_ROW)
        ga_dev = jax.device_put(
            np.stack(
                [
                    rng.integers(0, 2**32, gb_shape3, np.uint32)
                    & rng.integers(0, 2**32, gb_shape3, np.uint32)
                    for _ in range(8)
                ]
            )
        )
        gbc_dev = jax.device_put(
            np.stack(
                [
                    rng.integers(0, 2**32, gb_shape3, np.uint32)
                    & rng.integers(0, 2**32, gb_shape3, np.uint32)
                    for _ in range(24)
                ]
            )
        )

        @jax.jit
        def groupby_tally_once(ga, gbc, salt):
            return gbm_dev._counts_cross(jnp.bitwise_xor(ga, salt), gbc)

        _ = np.asarray(groupby_tally_once(ga_dev, gbc_dev, np.uint32(0)))
        t0 = time.perf_counter()
        outs = [
            groupby_tally_once(ga_dev, gbc_dev, np.uint32(i + 1))
            for i in range(TB)
        ]
        _ = np.asarray(outs[-1])  # one sync for the whole batch
        groupby_device_ms = (time.perf_counter() - t0) * 1000 / TB
        del ga_dev, gbc_dev

        # ---- tunnel RTT (dispatch + sync of a trivial op) ----
        tiny = jax.device_put(np.uint32(1))
        add1 = jax.jit(lambda x: x + 1)
        _ = int(add1(tiny))
        rtt_ms = _median_ms(lambda: int(add1(tiny)), 5)

        # ---- system numbers through api.query ----
        q_count = "Count(Intersect(Row(f=1), Row(f=2)))"
        got = api.query("bx", q_count)[0]  # warm: compile + stack build
        assert got == expect, (got, expect)
        system_ms = _median_ms(lambda: api.query("bx", q_count), 12)

        # multi-Count batching: 4 counts in one PQL request = ONE dispatch
        # + one host read — per-query system cost ~RTT/4
        q_multi = (
            "Count(Intersect(Row(f=1), Row(f=2)))"
            "Count(Union(Row(f=1), Row(f=2)))"
            "Count(Xor(Row(f=1), Row(f=2)))"
            "Count(Difference(Row(f=1), Row(f=2)))"
        )
        multi_got = api.query("bx", q_multi)  # warm
        assert multi_got[0] == expect, multi_got
        system_mq4_ms = _median_ms(lambda: api.query("bx", q_multi), 8) / 4

        # cross-request amortization: 8 concurrent single-Count clients
        # share dispatches through the group-commit batcher; per-query
        # latency approaches RTT/8 + device (VERDICT r4 #3)
        def concurrent_ms(query, n_threads=8, reps=4):
            def run_round():
                def client(errbox):
                    try:
                        for _ in range(reps):
                            api.query("bx", query)
                    except Exception as e:  # noqa: BLE001
                        errbox.append(e)

                errs: list = []
                threads = [
                    threading.Thread(target=client, args=(errs,))
                    for _ in range(n_threads)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                assert not errs, errs[:1]
                return (time.perf_counter() - t0) * 1000 / (n_threads * reps)

            run_round()  # warm: first round compiles the merged plan shapes
            return run_round()

        system_concurrent8_ms = concurrent_ms(q_count)

        (topn,) = api.query("bx", "TopN(f, n=100)")  # warm
        assert topn and topn[0].id in (1, 2), topn[:3]
        topn_ms = _median_ms(lambda: api.query("bx", "TopN(f, n=100)"), 5)

        q_topn_f = "TopN(f, Row(f=2), n=100)"
        (topn_f,) = api.query("bx", q_topn_f)  # warm: gather-bundle build
        assert topn_f and topn_f[0].id == 2, topn_f[:3]
        topn_filtered_ms = _median_ms(lambda: api.query("bx", q_topn_f), 5)
        from pilosa_tpu.exec.executor import TOPN_STATS

        for k in TOPN_STATS:
            TOPN_STATS[k] = 0
        api.query("bx", q_topn_f)
        assert TOPN_STATS["one_pass"] == 1, TOPN_STATS
        assert TOPN_STATS["tally_evals"] <= 2, TOPN_STATS

        (sum_vc,) = api.query("bx", "Sum(field=v)")  # warm (stack build)
        assert sum_vc.value == plane_sum, (sum_vc.value, plane_sum)
        sum_ms = _median_ms(lambda: api.query("bx", "Sum(field=v)"), 5)

        # config 4: GroupBy over 3 fields, 64 shards, 192 groups
        q_gb = "GroupBy(Rows(ga), Rows(gb), Rows(gc))"
        (groups,) = api.query("gbx", q_gb)  # warm
        assert len(groups) == 8 * 6 * 4, len(groups)
        groupby_ms = _median_ms(lambda: api.query("gbx", q_gb), 5)

        # ---- bench-coverage gap families (ROADMAP item 4) ----
        # Xor/Not/Shift plus BSI Min/Max/Range at the same 1B-column
        # config as the existing intersect/sum numbers — these shapes
        # had no baselines, so regressions in their lowering were
        # invisible. Asserted against host truth like everything else.
        def _popc(words) -> int:
            return int(
                np.bitwise_count(words).sum()
                if hasattr(np, "bitwise_count")
                else np.unpackbits(
                    np.ascontiguousarray(words).view(np.uint8)
                ).sum()
            )

        q_xor = "Count(Xor(Row(f=1), Row(f=2)))"
        expect_xor = _popc(a_h ^ b_h)
        got = api.query("bx", q_xor)[0]  # warm
        assert got == expect_xor, (got, expect_xor)
        xor_ms = _median_ms(lambda: api.query("bx", q_xor), 5)

        # existence for Not: row words imported directly (track_columns
        # over 1B columns would be a second full position-wise ingest)
        ef = idx.existence_field()
        for s in range(n_shards):
            ef.import_row_words(0, s, a_h[s] | b_h[s])
        q_not = "Count(Not(Row(f=1)))"
        expect_not = _popc((a_h | b_h) & ~a_h)
        got = api.query("bx", q_not)[0]  # warm
        assert got == expect_not, (got, expect_not)
        not_ms = _median_ms(lambda: api.query("bx", q_not), 5)

        q_shift = "Count(Shift(Row(f=1), n=1))"
        got = api.query("bx", q_shift)[0]  # warm
        # the carry out of the last shard lands in its (materialized)
        # successor, so no bit is lost and the count is exactly row 1's
        assert got == _popc(a_h), (got, _popc(a_h))
        shift_ms = _median_ms(lambda: api.query("bx", q_shift), 5)

        # BSI aggregates ride the plane-streamed lowering (ISSUE 15):
        # counter-asserted dispatch shape — ONE compiled dispatch + ONE
        # scalar-sized host read per warm aggregate at this depth-8 /
        # 954-shard config (exactly one budget chunk, exactly one slab)
        from pilosa_tpu.exec import plan as planmod_b

        def _one_dispatch(q):
            ev0 = planmod_b.STATS["evals"]
            rd0 = planmod_b.STATS["host_reads"]
            (res,) = api.query("bx", q)
            assert planmod_b.STATS["evals"] - ev0 == 1, (
                q, planmod_b.STATS["evals"] - ev0,
            )
            assert planmod_b.STATS["host_reads"] - rd0 == 1, (
                q, planmod_b.STATS["host_reads"] - rd0,
            )
            return res

        (min_vc,) = api.query("bx", "Min(field=v)")  # warm
        assert min_vc.count > 0, min_vc
        assert _one_dispatch("Min(field=v)").value == min_vc.value
        bsi_min_ms = _median_ms(lambda: api.query("bx", "Min(field=v)"), 5)
        (max_vc,) = api.query("bx", "Max(field=v)")  # warm
        assert max_vc.count > 0 and max_vc.value >= min_vc.value, (
            min_vc, max_vc,
        )
        assert _one_dispatch("Max(field=v)").value == max_vc.value
        bsi_max_ms = _median_ms(lambda: api.query("bx", "Max(field=v)"), 5)
        assert _one_dispatch("Sum(field=v)").value == plane_sum
        q_bsi_range = f"Count(Row(v > {(1 << BSI_DEPTH) // 2}))"
        api.query("bx", q_bsi_range)  # warm
        _one_dispatch(q_bsi_range)
        bsi_range_ms = _median_ms(lambda: api.query("bx", q_bsi_range), 5)

        # HBM-pressure eviction: budget below the ~250 MB count working
        # set; results must stay correct while operands re-stage per query.
        # With extent-granular paging (pilosa_tpu/hbm/) only the evicted
        # slices re-upload — hbm_restage_mb_per_query records the actual
        # PCIe traffic per query under pressure (monolithic staging
        # re-shipped the full working set every time: the 30-40x cliff).
        from pilosa_tpu.hbm import residency as hbm_res

        old_budget = DEVICE_CACHE.budget_bytes
        DEVICE_CACHE.budget_bytes = 128 << 20
        DEVICE_CACHE.clear()
        got = api.query("bx", q_count)[0]
        assert got == expect, (got, expect)
        restage0 = hbm_res.stats_snapshot()["restage_bytes"]
        evict_reps = 5
        hbm_evict_count_ms = _median_ms(
            lambda: api.query("bx", q_count), evict_reps
        )
        hbm_restage_mb_per_query = (
            hbm_res.stats_snapshot()["restage_bytes"] - restage0
        ) / evict_reps / (1 << 20)
        DEVICE_CACHE.budget_bytes = old_budget
        DEVICE_CACHE.clear()
        got = api.query("bx", q_count)[0]  # restore + re-verify
        assert got == expect, (got, expect)

        # dirty-extent restage (ISSUE 5): a single-shard write into a warm
        # working set, then the same count — only the covering extent(s)
        # re-stage, not the ~250 MB stack set (monolithic invalidation
        # re-shipped everything from the write side)
        restage0 = hbm_res.stats_snapshot()["restage_bytes"]
        f.set_bit(1, 7)  # shard 0 of a count operand
        api.query("bx", q_count)
        ingest_dirty_restage_mb = (
            hbm_res.stats_snapshot()["restage_bytes"] - restage0
        ) / (1 << 20)

        # ---- versioned result cache: the warm path (ISSUE 14) ----
        # the bench server runs with the cache disabled so every number
        # above is an execution cost; this section enables it and
        # measures the canonical dashboard steady state — the SAME
        # Count/TopN re-issued while a writer stages continuous ingest
        # into another field — plus the in-place Count repair after a
        # set-only burst into the cached row itself. Counter-asserted:
        # revalidated hits issue zero compiled dispatches, zero blocking
        # device reads, and zero host->device upload bytes.
        from pilosa_tpu.core.resultcache import RESULT_CACHE
        from pilosa_tpu.exec import plan as planmod_c

        api.create_field("bx", "cache_tgt")
        RESULT_CACHE.configure(budget_bytes=64 << 20, repair=True)
        try:
            q_cached = [q_count, "TopN(f, n=100)"]
            for q in q_cached:
                api.query("bx", q)
                api.query("bx", q)  # repeat stores + first hit
            stop_w = threading.Event()
            werrs: list = []

            def cache_writer():
                wrng = np.random.default_rng(23)
                try:
                    while not stop_w.is_set():
                        cc = wrng.integers(
                            0, n_shards * SHARD_WIDTH, 20_000
                        ).astype(np.uint64)
                        api.import_bits(
                            "bx", "cache_tgt",
                            np.full(len(cc), 1, np.uint64), cc,
                        )
                except Exception as e:  # noqa: BLE001 - surfaced below
                    werrs.append(e)

            wt = threading.Thread(target=cache_writer)
            wt.start()
            time.sleep(0.2)
            ev0 = planmod_c.STATS["evals"]
            rd0 = planmod_c.STATS["host_reads"]
            up0 = hbm_res.stats_snapshot()["restage_bytes"]
            hit0 = RESULT_CACHE.stats_snapshot()["hits"]
            lat = []
            reps_c = 300
            for i in range(reps_c):
                t0 = time.perf_counter()
                api.query("bx", q_cached[i % 2])
                lat.append((time.perf_counter() - t0) * 1000)
            stop_w.set()
            wt.join(60)
            assert not werrs, werrs[:1]
            lat.sort()
            cached_query_p50_ms = lat[len(lat) // 2]
            cached_query_p99_ms = lat[int(len(lat) * 0.99)]
            assert (
                RESULT_CACHE.stats_snapshot()["hits"] - hit0 == reps_c
            ), "a repeat under disjoint-field ingest failed to revalidate"
            assert planmod_c.STATS["evals"] == ev0, "cached hit dispatched"
            assert planmod_c.STATS["host_reads"] == rd0, "cached hit read"
            assert (
                hbm_res.stats_snapshot()["restage_bytes"] == up0
            ), "cached hit uploaded operand bytes"
            assert cached_query_p50_ms < 1.0, cached_query_p50_ms

            # in-place Count repair: a set-only staged burst into the
            # cached row is patched from the merge barrier's word delta —
            # no operand re-read, no re-staging, no dispatch
            q_repair = "Count(Row(f=3))"
            base_rep = api.query("bx", q_repair)[0]
            assert api.query("bx", q_repair)[0] == base_rep
            # shard-local burst (the canonical ingest locality): a burst
            # smeared over all 954 shards instead measures the merge
            # barrier's per-shard extent-patch cascade, which dwarfs the
            # repair itself (the repair's marginal cost is the counter-
            # asserted zero below either way). Keep the burst STAGED:
            # the op-count snapshot trigger would merge it inside the
            # import call, leaving the barrier nothing to repair from —
            # a closed repair window, not a wrong answer (same idiom as
            # the merge-roofline section below)
            for fr in f.view("standard").fragments.values():
                fr.max_op_n = max(fr.max_op_n, 1 << 22)
            rc_cols = rng.integers(
                0, min(4, n_shards) * SHARD_WIDTH, 50_000
            ).astype(np.uint64)
            f.import_bits(np.full(len(rc_cols), 3, np.uint64), rc_cols)
            ev0 = planmod_c.STATS["evals"]
            rd0 = planmod_c.STATS["host_reads"]
            up0 = hbm_res.stats_snapshot()["restage_bytes"]
            rp0 = RESULT_CACHE.stats_snapshot()["repairs"]
            t0 = time.perf_counter()
            repaired = api.query("bx", q_repair)[0]
            count_repair_ms = (time.perf_counter() - t0) * 1000
            assert RESULT_CACHE.stats_snapshot()["repairs"] > rp0
            assert planmod_c.STATS["evals"] == ev0, "repair dispatched"
            assert planmod_c.STATS["host_reads"] == rd0, "repair read device"
            assert (
                hbm_res.stats_snapshot()["restage_bytes"] == up0
            ), "repair re-staged operand bytes"
            RESULT_CACHE.reset()
            fresh = api.query("bx", q_repair)[0]
            assert repaired == fresh, (repaired, fresh)
        finally:
            RESULT_CACHE.reset()
            RESULT_CACHE.configure(budget_bytes=0)

        # ---- deferred-delta merge barrier roofline (ISSUE 9) ----
        # the read barrier a staged burst pays: per-fragment host merges
        # (the pre-ISSUE-9 path, ~a dozen small-numpy calls + a lock per
        # staged fragment) vs the cross-fragment barrier (ONE batched
        # sort/dedup pass for the whole burst, core/merge.py). The burst
        # shape is the classic low-cardinality ingest: a handful of hot
        # rows spread across every shard — per-FRAGMENT overhead is
        # exactly what the barrier amortizes. merge_barrier_ms rides the
        # shipped AUTO crossover (host pass on a CPU dev host, device
        # program on an accelerator); the forced-device run below pins
        # the one-launch contract on the compiled program itself.
        from pilosa_tpu.core import merge as merge_mod

        std = f.view("standard")
        burst_bits = 200_000
        # keep the roofline bursts STAGED: the op-count snapshot trigger
        # would otherwise merge them eagerly mid-section (in-memory
        # snapshots are cheap resets, but they'd empty the barrier)
        for fr in std.fragments.values():
            fr.max_op_n = max(fr.max_op_n, 1 << 22)

        def stage_burst():
            r = rng.integers(3, 8, burst_bits).astype(np.uint64)
            c = rng.integers(0, n_shards * SHARD_WIDTH, burst_bits).astype(
                np.uint64
            )
            f.import_bits(r, c)

        stage_burst()  # warm: touched rows get stored sparse content
        std.sync_pending()
        for fr in std.fragments.values():
            fr.sync_pending_now()  # materialize overlays: clean baseline
        stage_burst()
        frags = [fr for fr in std.fragments.values() if fr._pending_n]
        t0 = time.perf_counter()
        for fr in frags:
            fr.sync_pending_now()
        merge_perfrag_host_ms = (time.perf_counter() - t0) * 1000
        stage_burst()
        merge_mod.reset_stats()
        t0 = time.perf_counter()
        std.sync_pending()
        merge_barrier_ms = (time.perf_counter() - t0) * 1000
        msnap = merge_mod.stats_snapshot()
        assert msnap["barriers"] == 1, msnap
        # the deferred row-store materialization the barrier parked
        # (installed at each fragment's next HOST read; the device path
        # reads patched extents and never pays it) — reported so the
        # barrier number is honest about what moved off the write path
        t0 = time.perf_counter()
        for fr in std.fragments.values():
            fr.sync_pending_now()
        merge_install_ms = (time.perf_counter() - t0) * 1000
        # forced-device: the 954-fragment burst pays ONE program launch
        merge_mod.configure(device_threshold=0)
        stage_burst()  # warm: compiles the merge program's pow2 bucket
        std.sync_pending()
        stage_burst()
        merge_mod.reset_stats()
        t0 = time.perf_counter()
        std.sync_pending()
        merge_barrier_device_ms = (time.perf_counter() - t0) * 1000
        msnap = merge_mod.stats_snapshot()
        assert msnap["barriers"] == 1 and msnap["device"] == 1, msnap
        merge_mod.configure(device_threshold=None)  # back to AUTO

        # ---- smeared-burst extent-patch cascade (ISSUE 15 satellite) ----
        # round-10's named caveat: a 50k-position burst smeared over all
        # 954 shards paid one `.at[].set` FULL-EXTENT copy per dirty
        # shard in the merge barrier's patch cascade (~11.6 s measured).
        # The cascade is now batched per extent — one gather|OR|scatter
        # per resident entry — so the barrier is O(extents) device ops.
        api.query("bx", q_count)  # re-warm operand extents at live versions
        psnap0 = hbm_res.stats_snapshot()
        smear_cols = rng.integers(
            0, n_shards * SHARD_WIDTH, 50_000
        ).astype(np.uint64)
        f.import_bits(np.full(len(smear_cols), 1, np.uint64), smear_cols)
        t0 = time.perf_counter()
        std.sync_pending()
        mixed_patch_cascade_ms = (time.perf_counter() - t0) * 1000
        psnap1 = hbm_res.stats_snapshot()
        patch_cascade_patches = (
            psnap1["extent_patches"] - psnap0["extent_patches"]
        )
        patch_cascade_batches = (
            psnap1["extent_patch_batches"] - psnap0["extent_patch_batches"]
        )
        # O(extents) contract, asserted for real: the batching engaged
        # (at least one scatter-bearing patch), the cascade issued FAR
        # fewer device scatters than the ~954 dirty shards (the old
        # path's .at[].set count), and the wall time is at least 10x
        # under the measured 11.6 s per-shard baseline (ISSUE 15
        # acceptance; measured ~0.24 s on this host)
        smear_dirty = len({int(c) // SHARD_WIDTH for c in smear_cols})
        assert 0 < patch_cascade_batches < smear_dirty // 4, (
            patch_cascade_batches, smear_dirty,
        )
        assert mixed_patch_cascade_ms < 11_600 / 10, mixed_patch_cascade_ms
        got_after_smear = api.query("bx", q_count)[0]
        DEVICE_CACHE.clear()  # exactness vs a cold full re-stage
        got_cold = api.query("bx", q_count)[0]
        assert got_after_smear == got_cold, (got_after_smear, got_cold)

        # ---- sustained mixed read/write (the production workload) ----
        # continuous staged ingest against one index while Count/TopN
        # queries stream in: every query's read barrier merges whatever
        # the writer staged since the last one. Throughput and query
        # tail latency are read from the PR 6 flight-recorder histograms
        # (per-index query_ms series).
        api.create_index("mx")
        api.create_field("mx", "f")
        mf = srv.holder.index("mx").field("f")
        m_shape = (MIXED_SHARDS, WORDS_PER_ROW)
        mw = rng.integers(0, 2**32, m_shape, np.uint32)
        for s in range(MIXED_SHARDS):
            mf.import_row_words(1, s, mw[s] & (mw[s] >> np.uint32(1)))
            mf.import_row_words(2, s, mw[s] & (mw[s] << np.uint32(1)))
        q_mix_count = "Count(Row(f=1))"
        q_mix_topn = "TopN(f, n=50)"
        api.query("mx", q_mix_count)  # warm: stage + compile
        api.query("mx", q_mix_topn)
        # drop the warm-up observations so the histogram holds ONLY
        # queries issued under ingest pressure
        srv.stats.registry.drop_label("index", "mx")
        stop = threading.Event()
        wrote = [0]
        writer_errs = []

        def mixed_writer():
            try:
                wrng = np.random.default_rng(99)
                batch = 20_000
                while not stop.is_set():
                    r = wrng.integers(3, 33, batch).astype(np.uint64)
                    c = wrng.integers(
                        0, MIXED_SHARDS * SHARD_WIDTH, batch
                    ).astype(np.uint64)
                    mf.import_bits(r, c)
                    wrote[0] += batch
            except BaseException as e:  # noqa: BLE001 - fail the bench
                writer_errs.append(e)

        mb0 = merge_mod.stats_snapshot()
        patches0 = hbm_res.stats_snapshot()["extent_patches"]
        wt = threading.Thread(target=mixed_writer)
        t0 = time.perf_counter()
        wt.start()
        try:
            mixed_queries = 0
            while time.perf_counter() - t0 < MIXED_SECONDS:
                api.query("mx", q_mix_count)
                api.query("mx", q_mix_topn)
                mixed_queries += 2
        finally:
            stop.set()
            wt.join()
        assert not writer_errs, writer_errs  # a dead writer fakes the numbers
        mixed_elapsed = time.perf_counter() - t0
        ingest_mixed_bits_mps = wrote[0] / mixed_elapsed / 1e6
        reg = srv.stats.registry
        query_p50_under_ingest_ms = reg.quantile(
            "query_ms", 0.5, tags=("index:mx",)
        )
        query_p99_under_ingest_ms = reg.quantile(
            "query_ms", 0.99, tags=("index:mx",)
        )
        mb1 = merge_mod.stats_snapshot()
        mixed_merge_barriers = mb1["barriers"] - mb0["barriers"]
        mixed_merge_barrier_ms_mean = (
            (mb1["barrier_ms"] - mb0["barrier_ms"]) / mixed_merge_barriers
            if mixed_merge_barriers
            else 0.0
        )
        mixed_extent_patches = (
            hbm_res.stats_snapshot()["extent_patches"] - patches0
        )

        # ---- time-quantum range path (ROADMAP item 5 baseline) ----
        from datetime import datetime, timedelta

        from pilosa_tpu.core.field import FIELD_TYPE_TIME, FieldOptions

        api.create_index("tqx")
        tf = srv.holder.index("tqx").create_field(
            "e", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD")
        )
        tq_bits = 50_000
        t_base = datetime(2019, 1, 1)
        tq_rows = rng.integers(1, 5, tq_bits).astype(np.uint64)
        tq_cols = rng.integers(0, TQ_SHARDS * SHARD_WIDTH, tq_bits).astype(
            np.uint64
        )
        tq_days = rng.integers(0, 45, tq_bits)
        tf.import_bits(
            tq_rows,
            tq_cols,
            timestamps=[t_base + timedelta(days=int(d)) for d in tq_days],
        )
        q_tq = "Count(Range(e=1, 2019-01-05T00:00, 2019-01-20T00:00))"
        (tq_count,) = api.query("tqx", q_tq)  # warm
        assert int(tq_count) > 0, tq_count
        timeq_range_ms = _median_ms(lambda: api.query("tqx", q_tq), 5)
    finally:
        srv.stop()

    # replicated mixed read/write — the production write configuration
    # (ISSUE 12): replica_n=2 over two real-data-dir HTTP nodes with the
    # strict group-commit WAL on; its own harness, so it runs after the
    # in-memory node is down
    try:
        replicated = replicated_bench()
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        replicated = {"replicated_error": f"{type(e).__name__}: {e}"[:200]}

    # tiered storage (ISSUE 18): demote throughput, cold-query hydration
    # latency, beyond-budget serving — against a slow-wrapped local store
    try:
        tier_metrics = tier_bench()
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        tier_metrics = {"tier_error": f"{type(e).__name__}: {e}"[:200]}

    # cache coherence (ISSUE 19): leased vs revalidate warm fan-out hits,
    # subscription push latency, monotone tree repair — its own harnesses
    try:
        coherence_metrics = coherence_bench()
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        coherence_metrics = {
            "coherence_error": f"{type(e).__name__}: {e}"[:200]
        }

    # config 5 stand-in: virtual-mesh scaling curve in a CPU subprocess
    # (hermetic from the TPU tunnel; same env recipe as tests/conftest.py)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-scaling"],
            capture_output=True,
            text=True,
            timeout=900,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        mesh_scaling = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        mesh_scaling = [{"error": f"{type(e).__name__}: {e}"[:200]}]

    # mesh-group certification (ISSUE 10): 16- and 32-virtual-device
    # clusters, one ICI domain, Count folded into ONE compiled dispatch
    # + ONE blocking host read (counter-asserted in the child) and
    # bit-identical to the HTTP fan-out — the numbers the north-star
    # arithmetic now rests on (tools/mesh_cert.py; the cert env clears
    # XLA_FLAGS itself, one subprocess per device count)
    mesh_group: dict = {}
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools", "mesh_cert.py")],
            capture_output=True, text=True, timeout=1800, env=env, cwd=here,
        )
        cert = json.loads(out.stdout.strip())
        for rnd in cert.get("rounds", []):
            n = rnd.get("n_devices")
            mesh_group[f"mesh{n}_count_ms"] = rnd.get("mesh_count_ms")
            mesh_group[f"mesh{n}_http_count_ms"] = rnd.get("http_count_ms")
            mesh_group[f"mesh{n}_dispatches"] = rnd.get("dispatches")
            mesh_group[f"mesh{n}_host_reads"] = rnd.get("host_reads")
        mesh_group["ok"] = cert.get("ok", False)
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        mesh_group = {"error": f"{type(e).__name__}: {e}"[:200]}

    # ---- CPU comparator: vectorized numpy popcount, same data ----
    if hasattr(np, "bitwise_count"):
        def cpu_count():
            return int(np.bitwise_count(a_h & b_h).sum())
    else:
        lut = np.array([bin(i).count("1") for i in range(1 << 16)], np.uint16)
        def cpu_count():
            return int(lut[(a_h & b_h).view(np.uint16)].sum(dtype=np.int64))

    got = cpu_count()
    assert got == expect, (got, expect)
    cpu_ms = _median_ms(cpu_count, 3)

    print(
        json.dumps(
            {
                "metric": "count_intersect_1b_cols_per_query_ms",
                "value": round(device_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / device_ms, 2),
                "extras": {
                    "system_ms": round(system_ms, 3),
                    "system_concurrent8_ms": round(system_concurrent8_ms, 3),
                    "rtt_ms": round(rtt_ms, 3),
                    "device_gbps": round(device_gbps, 1),
                    "device_burst_ms": round(burst_ms, 4),
                    "device_burst_gbps": round(burst_gbps, 1),
                    "device_mq4_ms": round(mq_ms, 4),
                    "device_mq4_gbps_effective": round(mq_gbps_effective, 1),
                    "system_mq4_ms": round(system_mq4_ms, 3),
                    "cpu_baseline_ms": round(cpu_ms, 3),
                    "ingest_bits_mps": round(ingest_bits_mps, 2),
                    "ingest_bits_mps_warm": round(ingest_bits_mps_warm, 2),
                    "ingest_roaring_mbps": round(ingest_roaring_mbps, 1),
                    "ingest_dirty_restage_mb": round(
                        ingest_dirty_restage_mb, 2
                    ),
                    "merge_barrier_ms": round(merge_barrier_ms, 3),
                    "merge_perfrag_host_ms": round(
                        merge_perfrag_host_ms, 3
                    ),
                    "merge_barrier_device_ms": round(
                        merge_barrier_device_ms, 3
                    ),
                    "merge_install_ms": round(merge_install_ms, 3),
                    "ingest_mixed_bits_mps": round(
                        ingest_mixed_bits_mps, 2
                    ),
                    "query_p50_under_ingest_ms": round(
                        query_p50_under_ingest_ms, 3
                    ),
                    "query_p99_under_ingest_ms": round(
                        query_p99_under_ingest_ms, 3
                    ),
                    "mixed_queries": mixed_queries,
                    "mixed_merge_barriers": mixed_merge_barriers,
                    "mixed_merge_barrier_ms_mean": round(
                        mixed_merge_barrier_ms_mean, 3
                    ),
                    "mixed_extent_patches": mixed_extent_patches,
                    "mixed_patch_cascade_ms": round(
                        mixed_patch_cascade_ms, 3
                    ),
                    "patch_cascade_patches": patch_cascade_patches,
                    "patch_cascade_batches": patch_cascade_batches,
                    **replicated,
                    **tier_metrics,
                    **coherence_metrics,
                    "timeq_range_ms": round(timeq_range_ms, 3),
                    "topn_n100_954shards_ms": round(topn_ms, 3),
                    "topn_filtered_n100_ms": round(topn_filtered_ms, 3),
                    "topn_filtered_device_ms": round(topn_filtered_device_ms, 3),
                    "xor_ms": round(xor_ms, 3),
                    "not_ms": round(not_ms, 3),
                    "shift_ms": round(shift_ms, 3),
                    "bsi_min_ms": round(bsi_min_ms, 3),
                    "bsi_max_ms": round(bsi_max_ms, 3),
                    "bsi_range_ms": round(bsi_range_ms, 3),
                    "cached_query_p50_ms": round(cached_query_p50_ms, 4),
                    "cached_query_p99_ms": round(cached_query_p99_ms, 4),
                    "count_repair_ms": round(count_repair_ms, 3),
                    "bsi_sum_1b_cols_ms": round(sum_ms, 3),
                    "bsi_sum_device_ms": round(bsi_sum_device_ms, 3),
                    "groupby_3f_64shards_ms": round(groupby_ms, 3),
                    "groupby_device_ms": round(groupby_device_ms, 3),
                    "hbm_evict_count_ms": round(hbm_evict_count_ms, 3),
                    "hbm_restage_mb_per_query": round(
                        hbm_restage_mb_per_query, 2
                    ),
                    "mesh_scaling": mesh_scaling,
                    "mesh_group": mesh_group,
                    "batch": BATCH,
                    "n_shards": n_shards,
                },
            }
        )
    )


if __name__ == "__main__":
    if "--mesh-scaling" in sys.argv:
        sys.exit(mesh_scaling_main())
    if "--replicated" in sys.argv:
        # the replicated write-path section alone (quick durability runs)
        print(json.dumps(replicated_bench()))
        sys.exit(0)
    if "--coherence" in sys.argv:
        # the coherence-plane section alone (quick lease/push runs)
        print(json.dumps(coherence_bench()))
        sys.exit(0)
    sys.exit(main())
